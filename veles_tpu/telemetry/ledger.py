"""Persistent performance ledger + noise-aware regression sentinel.

TVM's central discipline (PAPERS.md) — every measurement lands in a
persistent store the optimizer can train against — applied to *all* of
this repo's performance numbers, not just the tuner's kernel winners.
Before this module, ``last_known_good`` was an ad-hoc blob inside
``BENCH_r0x.json``, chaos-harness gate numbers (storm_ms_per_tok,
failover detect latency, stall p99s) evaporated after each run, and a
silent perf regression would only be caught by a human re-reading JSON.

The ledger is an append-only JSONL file; every record is one line:

    {"schema": 1, "ts": ..., "metric": ..., "value": ..., "unit": ...,
     "workload": ..., "backend": "tpu:4", "mesh": "-", "dtype": "bf16",
     "better": "lower"|"higher", "source": "bench.serve",
     "target": {"id": ..., "goal": ..., "better": ...}|null,
     "components": {"compute_ms": ..., ...}|null, ...}

Records are keyed the tuner's way (``veles_tpu.tuner.make_key``):
``metric | workload | backend:devcount | mesh-topology | dtype`` — the
same five axes that decide whether two kernel timings are comparable
decide whether two ledger rows are.  Appends are atomic (one
``os.write`` on an ``O_APPEND`` fd — concurrent writers interleave
whole lines, never bytes) and **fail-soft** like the PR 3 metrics
sink: ledger I/O can never fail the run it observes; an unwritable
directory degrades to in-memory history.

**Targets** are pre-registered here, not in bench-phase code: the
:data:`TARGETS` registry is THE declaration (``bench.py`` reads its
goal constants from it, each appended row carries the target it
answers, and the VL12xx lint — :mod:`veles_tpu.analysis.perf_lint` —
cross-checks declared-vs-measured both ways).

**Sentinel**: every fresh append is compared against the key's history
using a median/MAD band — ``median ± band_mads · 1.4826 · MAD``,
floored at ``min_rel_band`` of the median so a freakishly quiet
history cannot turn run-to-run noise into alarms.  A value outside the
band on the worse side emits a ``perf.regression`` flight event and
bumps ``veles_perf_regressions_total``; meeting/missing the declared
target emits ``perf.target_met``; signed drift vs the median lands on
the ``veles_perf_drift{metric}`` gauge.  When records carry a
``components`` decomposition (the step-anatomy layer,
:mod:`veles_tpu.telemetry.anatomy`), the verdict names the component
whose share grew the most — "step got slower" becomes "dispatch-queue
share doubled".

Knobs: ``root.common.perf.*`` (docs/config_reference.md).  Surfaces:
``veles-tpu-perf`` (report/diff/gate/targets), the web-status
``/api/perf`` panel, docs/perf.md "Performance ledger & regression
sentinel".  Import cost is stdlib-only (jax only consulted for the
backend descriptor when already loaded, like flight._process_index)."""

import dataclasses
import json
import os
import sys
import threading
import time

#: current record schema; readers migrate older shapes forward (a
#: record with no "schema" field is v0: pre-ledger blob rows whose
#: timestamp key was "when" and which carried no keying axes)
SCHEMA = 1

_MAD_SCALE = 1.4826   # MAD -> sigma-equivalent for normal noise


# --------------------------------------------------------------- targets
@dataclasses.dataclass(frozen=True)
class Target:
    """One pre-registered performance target: the number a future TPU
    window must answer, declared HERE (not inline in a bench phase) so
    the declared-vs-measured contract is lintable."""
    metric: str     #: ledger metric the target gates
    goal: float     #: the pre-registered bar
    better: str     #: "lower" | "higher" — which side of goal wins
    unit: str       #: unit of goal (display only)
    source: str     #: who measures it, e.g. "bench.serve"
    note: str = ""  #: provenance — where the bar was argued for

    def met(self, value):
        return (value <= self.goal if self.better == "lower"
                else value >= self.goal)


#: THE target registry (ROADMAP item 1's pre-registered bars moved out
#: of bench-phase code).  bench.py emits the legacy ``target_*`` phase
#: keys FROM these values, so the driver contract is unchanged.
TARGETS = (
    Target("serve_int8_vs_bf16_x", 1.5, "higher", "x", "bench.serve",
           "int8 >= 1.5x bf16 ms/tok on the memory-bound flagship "
           "width (BENCH_r05 measured 1.13x pre-quantized-depth)"),
    Target("serve_seg_stall_x", 4.0, "lower", "x", "bench.serve",
           "segmented-prefill p99 decode stall <= 4x the base cadence "
           "while a long prompt admits mid-stream"),
    Target("serve_cost_vs_rr_x", 1.0, "higher", "x", "bench.serve",
           "cost-weighted routing must not lose to round-robin under "
           "the skewed-length storm (rr/cost ms-per-tok ratio)"),
    Target("flash_bwd_vs_xla_x", 1.0, "lower", "x", "bench.flash",
           "tuned flash bwd <= XLA (last-known-good 6.95 ms vs 3.99 "
           "— the flashtune sweep's job, ROADMAP item 1)"),
    Target("lm_large_mfu", 0.44, "higher", "MFU", "bench.lm_large",
           "the lm_large_ladder chase from MFU 0.37 toward the 0.44 "
           "bf16-gemm ceiling (ROADMAP item 1)"),
)

TARGETS_BY_METRIC = {t.metric: t for t in TARGETS}


def target_goal(metric, default=None):
    """The declared goal for ``metric`` — bench phases emit their
    legacy ``target_*`` keys through this, so the registry is the one
    source of truth."""
    t = TARGETS_BY_METRIC.get(metric)
    return default if t is None else t.goal


#: bench.py ``line`` keys that are ledger rows: key -> (unit, better,
#: phase).  Keys absent here (flags, metadata, nested blobs) stay out
#: of the ledger.  The serve/flash ``*_x`` ratios are derived in
#: bench.main() from the raw ms keys so their targets are judgeable.
BENCH_ROWS = {
    "value": ("GFLOP/s", "higher", "gemm"),
    "vs_baseline": ("x", "higher", "gemm"),
    "gemm_bf16_gflops": ("GFLOP/s", "higher", "gemm"),
    "gemm_bf16_mfu": ("MFU", "higher", "gemm"),
    "gemm_precision_overhead_pct": ("%", "lower", "gemm"),
    "mlp_step_ms": ("ms", "lower", "mlp"),
    "mlp_step_fused_ms": ("ms", "lower", "mlp"),
    "alexnet_samples_per_sec": ("samples/s", "higher", "alexnet"),
    "lm_tokens_per_sec": ("tok/s", "higher", "lm"),
    "lm_mfu": ("MFU", "higher", "lm"),
    "lm_large_tokens_per_sec": ("tok/s", "higher", "lm_large"),
    "lm_large_mfu": ("MFU", "higher", "lm_large"),
    "kohonen_ms_per_step": ("ms", "lower", "kohonen"),
    "kohonen_sweep_speedup": ("x", "higher", "kohonen"),
    "flash_ms_bf16": ("ms", "lower", "flash"),
    "flash_ms_bf16_xla": ("ms", "lower", "flash"),
    "flash_ms_bwd": ("ms", "lower", "flash"),
    "flash_ms_bwd_xla": ("ms", "lower", "flash"),
    "flash_bwd_vs_xla_x": ("x", "lower", "flash"),
    "flash_ms_long_t8192": ("ms", "lower", "flash"),
    "flash_ms_long_t8192_xla": ("ms", "lower", "flash"),
    "beam_ms_per_pos_t4096": ("ms", "lower", "beam"),
    "serve_ms_per_tok_bf16": ("ms", "lower", "serve"),
    "serve_ms_per_tok_int8": ("ms", "lower", "serve"),
    "serve_int8_vs_bf16_x": ("x", "higher", "serve"),
    "serve_seg_stall_x": ("x", "lower", "serve"),
    "serve_cost_vs_rr_x": ("x", "higher", "serve"),
}


# ---------------------------------------------------------------- keying
def _backend_descriptor():
    """``backend:devcount`` the tuner's way when jax is already up;
    a cheap env-derived guess otherwise (the ledger must stay
    importable — and appendable — without touching jax)."""
    if "jax" in sys.modules:
        try:
            from veles_tpu.tuner import mesh_descriptor
            return mesh_descriptor().split("/")[0]
        except Exception:   # noqa: BLE001 — keying must not raise
            pass
    plat = os.environ.get("JAX_PLATFORMS", "cpu").split(",")[0]
    return "%s:?" % (plat or "cpu")


def _mesh_axes():
    if "jax" in sys.modules:
        try:
            from veles_tpu.tuner import mesh_descriptor
            desc = mesh_descriptor()
            if "/" in desc:
                return desc.split("/", 1)[1]
        except Exception:   # noqa: BLE001
            pass
    return "-"


def key_of(record):
    """``metric | workload | backend:devcount | mesh-topology | dtype``
    — the tuner's keying discipline (tuner.make_key) over the ledger's
    five comparability axes."""
    return "|".join((str(record.get("metric", "?")),
                     str(record.get("workload", "-")),
                     str(record.get("backend", "-")),
                     str(record.get("mesh", "-")),
                     str(record.get("dtype", "-"))))


def _migrate(record):
    """Upgrade one parsed record to the current schema, in place-ish.
    v0 (no "schema"): pre-ledger rows used "when" for the timestamp
    and carried no keying axes — fill the axes with the unkeyed
    defaults so v0 history still groups with v1 appends of the same
    metric."""
    if not isinstance(record, dict) or "metric" not in record:
        return None
    ver = record.get("schema", 0)
    if ver > SCHEMA:            # from the future: keep what we parse
        return record
    if ver < 1:
        record = dict(record)
        if "when" in record and "ts" not in record:
            record["ts"] = record.pop("when")
        for axis in ("workload", "backend", "mesh", "dtype"):
            record.setdefault(axis, "-")
        record["schema"] = SCHEMA
    return record


def _infer_better(unit, better=None):
    if better in ("lower", "higher"):
        return better
    u = (unit or "").lower()
    if u in ("ms", "s", "us", "ms/tok", "%") or u.startswith("ms"):
        return "lower"
    return "higher"


def _median(vals):
    s = sorted(vals)
    n = len(s)
    if not n:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


# ---------------------------------------------------------------- ledger
class PerfLedger(object):
    """One JSONL performance ledger: atomic fail-soft appends, per-key
    history, and the median/MAD regression sentinel."""

    def __init__(self, path=None, registry=None):
        self.path = path or default_path()
        self._registry = registry
        self._lock = threading.Lock()
        self._mem = []           # appended this process (disk or not)
        self._disk_dead = False  # first write failure silences retries

    # -- knobs (root.common.perf.*, declared in config.py) -------------
    @staticmethod
    def _knob(name, default):
        try:
            from veles_tpu.config import root
            return root.common.perf.get(name, default)
        except Exception:   # noqa: BLE001 — knobs are advisory here
            return default

    def _reg(self):
        if self._registry is None:
            from veles_tpu import telemetry
            self._registry = telemetry.registry
        return self._registry

    # -- reading --------------------------------------------------------
    def records(self, metric=None, key=None):
        """All parseable records, disk first then this process's
        unpersisted in-memory tail, migrated to the current schema and
        optionally filtered by metric or full key."""
        out = []
        try:
            with open(self.path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = _migrate(json.loads(line))
                    except ValueError:
                        continue   # torn/garbage line: skip, not fatal
                    if rec is not None:
                        out.append(rec)
        except OSError:
            pass
        with self._lock:
            if self._disk_dead:
                out.extend(self._mem)
        if metric is not None:
            out = [r for r in out if r.get("metric") == metric]
        if key is not None:
            out = [r for r in out if key_of(r) == key]
        return out

    def by_key(self):
        """{key: [records, oldest first]} over the whole ledger."""
        groups = {}
        for rec in self.records():
            groups.setdefault(key_of(rec), []).append(rec)
        return groups

    def history(self, key, limit=None):
        recs = self.records(key=key)
        limit = limit or int(self._knob("history", 64))
        return recs[-limit:]

    # -- sentinel -------------------------------------------------------
    def assess(self, record, prior=None):
        """Noise-aware verdict of ``record`` against its key's prior
        history and its declared target.  Pure function of its inputs
        (no I/O when ``prior`` is given) so tests and the CLI gate can
        replay it.  Returns::

            {"status": "regression"|"improved"|"ok"|"no_history",
             "n": len(prior), "median": ..., "mad": ..., "band": ...,
             "drift": signed fraction vs median, "better": ...,
             "target": goal|None, "target_met": bool|None,
             "component": worst-drifting component name|None}
        """
        if prior is None:
            prior = self.history(key_of(record))
            if prior and prior[-1] == record:   # already appended
                prior = prior[:-1]
        vals = [r.get("value") for r in prior
                if isinstance(r.get("value"), (int, float))]
        value = record.get("value")
        better = _infer_better(record.get("unit"),
                               record.get("better"))
        tgt = record.get("target") or None
        decl = TARGETS_BY_METRIC.get(record.get("metric"))
        goal = (tgt or {}).get("goal",
                               decl.goal if decl else None)
        verdict = {"status": "no_history", "n": len(vals),
                   "median": None, "mad": None, "band": None,
                   "drift": None, "better": better, "target": goal,
                   "target_met": None, "component": None}
        if isinstance(value, (int, float)) and goal is not None:
            verdict["target_met"] = (value <= goal if better == "lower"
                                     else value >= goal)
        min_hist = int(self._knob("min_history", 3))
        if len(vals) < min_hist or not isinstance(value, (int, float)):
            return verdict
        med = _median(vals)
        mad = _median([abs(v - med) for v in vals])
        band = max(float(self._knob("band_mads", 4.0)) * _MAD_SCALE
                   * mad,
                   float(self._knob("min_rel_band", 0.05)) * abs(med))
        drift = (value - med) / med if med else 0.0
        verdict.update(median=med, mad=mad, band=band,
                       drift=round(drift, 6))
        worse = (value > med + band if better == "lower"
                 else value < med - band)
        improved = (value < med - band if better == "lower"
                    else value > med + band)
        verdict["status"] = ("regression" if worse
                             else "improved" if improved else "ok")
        if worse:
            verdict["component"] = self._drifted_component(record,
                                                           prior)
        return verdict

    @staticmethod
    def _drifted_component(record, prior):
        """Name the component whose time grew the most vs its own
        history median — the step-anatomy attribution that turns "step
        got slower" into "dispatch-queue share doubled"."""
        comps = record.get("components")
        if not isinstance(comps, dict):
            return None
        hist = {}
        for rec in prior:
            pc = rec.get("components")
            if isinstance(pc, dict):
                for name, v in pc.items():
                    if isinstance(v, (int, float)):
                        hist.setdefault(name, []).append(v)
        worst, excess = None, 0.0
        for name, v in comps.items():
            if not isinstance(v, (int, float)) or name not in hist:
                continue
            delta = v - _median(hist[name])
            if delta > excess:
                worst, excess = name, delta
        return worst

    def _emit_verdict(self, record, verdict):
        """Flight events + gauges for one fresh verdict — the PR 3
        fail-soft emit path (observe, never abort)."""
        try:
            from veles_tpu.telemetry import flight
            reg = self._reg()
            metric = str(record.get("metric", "?"))
            if verdict.get("drift") is not None:
                reg.gauge(
                    "veles_perf_drift",
                    "signed drift of the freshest ledger append vs "
                    "its key's history median", ("metric",)).set(
                    verdict["drift"], metric=metric)
            if verdict["status"] == "regression":
                reg.counter(
                    "veles_perf_regressions_total",
                    "ledger appends outside their key's MAD noise "
                    "band on the worse side").inc()
                flight.record(
                    "perf.regression", metric=metric,
                    key=key_of(record), value=record.get("value"),
                    median=verdict["median"], band=verdict["band"],
                    drift=verdict["drift"],
                    component=verdict["component"],
                    source=record.get("source"))
            if verdict.get("target_met") is not None:
                flight.record(
                    "perf.target_met", metric=metric,
                    value=record.get("value"),
                    target=verdict["target"],
                    met=verdict["target_met"],
                    source=record.get("source"))
        except Exception:   # noqa: BLE001 — emit is observational
            pass

    # -- writing --------------------------------------------------------
    def append(self, metric, value, workload="-", dtype="-", mesh=None,
               backend=None, unit="", better=None, target=None,
               source="", components=None, ts=None, assess=True,
               **extra):
        """Append one measurement; returns the record with its
        sentinel ``verdict`` attached (the verdict is derived state —
        it never lands on disk), or None when even building the record
        failed.  NEVER raises: ledger I/O cannot fail the run it
        observes (fail-soft like the PR 3 sink)."""
        try:
            decl = TARGETS_BY_METRIC.get(metric)
            if target is None and decl is not None:
                target = {"id": decl.metric, "goal": decl.goal,
                          "better": decl.better}
            rec = {"schema": SCHEMA,
                   "ts": time.time() if ts is None else ts,
                   "metric": str(metric), "value": value,
                   "unit": unit, "workload": str(workload),
                   "backend": (backend if backend is not None
                               else _backend_descriptor()),
                   "mesh": str(mesh) if mesh is not None
                   else _mesh_axes(),
                   "dtype": str(dtype),
                   "better": _infer_better(unit, better),
                   "source": str(source), "target": target}
            if components:
                rec["components"] = components
            for k, v in extra.items():
                rec.setdefault(k, v)
            prior = self.history(key_of(rec)) if assess else None
            self._write(rec)
            with self._lock:
                self._mem.append(rec)
            if assess:
                verdict = self.assess(rec, prior)
                self._emit_verdict(rec, verdict)
                rec = dict(rec, verdict=verdict)
            return rec
        except Exception:   # noqa: BLE001 — fail-soft by contract
            return None

    def _write(self, rec):
        """One atomic line: a single O_APPEND write interleaves whole
        records under concurrent writers (POSIX append semantics), and
        the first OSError retires the disk path for the process —
        history keeps accumulating in memory."""
        if self._disk_dead:
            return
        line = (json.dumps(rec, sort_keys=True,
                           default=str) + "\n").encode("utf-8")
        try:
            d = os.path.dirname(self.path)
            if d and not os.path.isdir(d):
                os.makedirs(d, exist_ok=True)
            fd = os.open(self.path,
                         os.O_WRONLY | os.O_APPEND | os.O_CREAT,
                         0o644)
            try:
                os.write(fd, line)
            finally:
                os.close(fd)
        except OSError:
            self._disk_dead = True

    # -- bench integration ---------------------------------------------
    def append_bench_line(self, line, source="bench", ts=None):
        """Every measured ``bench.py`` phase row -> one ledger record
        carrying its pre-registered target (BENCH_ROWS is the row
        spec; zeros are "phase did not run", not measurements).
        Returns the number of rows appended."""
        n = 0
        for bench_key, (unit, better, phase) in BENCH_ROWS.items():
            v = line.get(bench_key)
            if not isinstance(v, (int, float)) \
                    or isinstance(v, bool) or not v:
                continue
            if self.append(bench_key, v, workload=phase, unit=unit,
                           better=better, dtype="-",
                           source="%s.%s" % (source, phase),
                           ts=ts) is not None:
                n += 1
        return n

    def last_known_good_line(self):
        """The latest value per bench row reconstructed from the
        ledger — bench.py's ``last_known_good`` emission reads THIS
        (the one source of truth; ``.bench_last_good.json`` is only
        the fallback for checkouts without a ledger).  ``measured_at``
        is the newest row's date; per-key dates ride in
        ``carried_from`` when rows span runs (the _merge_cache
        honesty rule)."""
        latest, stamp = {}, {}
        for rec in self.records():
            k = rec.get("metric")
            if k in BENCH_ROWS and isinstance(rec.get("value"),
                                              (int, float)):
                latest[k] = rec["value"]
                stamp[k] = rec.get("ts", 0)
        if not latest:
            return None
        newest = max(stamp.values())
        carried = {
            k: time.strftime("%Y-%m-%d %H:%M:%S",
                             time.localtime(t))
            for k, t in stamp.items() if newest - t > 86400.0}
        out = dict(latest)
        out["measured_at"] = time.strftime("%Y-%m-%d %H:%M:%S",
                                           time.localtime(newest))
        if carried:
            out["carried_from"] = carried
        return out


# --------------------------------------------------- module-level surface
def default_path():
    """Ledger path resolution: ``root.common.perf.ledger`` knob >
    ``VELES_TPU_PERF_LEDGER`` env > ``<dirs.cache>/perf_ledger.jsonl``
    (next to the tuner's winners — the other persistent measurement
    store)."""
    try:
        from veles_tpu.config import root
        knob = root.common.perf.get("ledger", None)
        if knob:
            return str(knob)
        cache = root.common.dirs.get("cache", None)
    except Exception:   # noqa: BLE001
        cache = None
    env = os.environ.get("VELES_TPU_PERF_LEDGER")
    if env:
        return env
    if not cache:
        cache = os.path.join(os.path.expanduser("~"), ".veles_tpu",
                             "cache")
    return os.path.join(cache, "perf_ledger.jsonl")


_default = None
_default_lock = threading.Lock()


def default():
    """The process ledger (resolved once; pass an explicit
    :class:`PerfLedger` to target another file)."""
    global _default
    with _default_lock:
        if _default is None or _default.path != default_path():
            _default = PerfLedger()
        return _default


def record_value(metric, value, **kwargs):
    """Fail-soft convenience append to the process ledger — the hook
    trainers/harnesses call inline (``root.common.perf.enabled``
    gates it; returns the record+verdict or None)."""
    try:
        from veles_tpu.config import root
        if not root.common.perf.get("enabled", True):
            return None
        return default().append(metric, value, **kwargs)
    except Exception:   # noqa: BLE001 — never fail the caller
        return None


def migrate_bench_blob(blob, ts=None, source="bench.migrate"):
    """``last_known_good`` blob ({bench key: value}) -> schema-1
    records, the BENCH_r0x seeding path (tools + tests).  Returns the
    record list WITHOUT writing — callers append or dump them."""
    if ts is None:
        measured_at = blob.get("measured_at")
        ts = 0.0
        if measured_at:
            try:
                ts = time.mktime(time.strptime(measured_at,
                                               "%Y-%m-%d %H:%M:%S"))
            except ValueError:
                ts = 0.0
    out = []
    for bench_key, (unit, better, phase) in BENCH_ROWS.items():
        v = blob.get(bench_key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or not v:
            continue
        decl = TARGETS_BY_METRIC.get(bench_key)
        out.append({
            "schema": SCHEMA, "ts": ts, "metric": bench_key,
            "value": v, "unit": unit, "workload": phase,
            "backend": "tpu:1", "mesh": "-", "dtype": "-",
            "better": better, "source": "%s.%s" % (source, phase),
            "target": ({"id": decl.metric, "goal": decl.goal,
                        "better": decl.better} if decl else None)})
    return out
