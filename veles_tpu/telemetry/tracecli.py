"""``veles-tpu-trace`` — reconstruct one serving request's
cross-process timeline by trace id.

Two sources, one render:

* **live** (``--url``): GET the span-store endpoint of a fleet router
  (``{path}/trace/<id>`` — the router merges its own spans with every
  live replica's) or of a single replica/web-status process
  (``/api/trace/<id>``).  A replica the chaos monkey SIGKILLed simply
  contributes nothing; the router-side chain stays connected, so the
  timeline still validates gapless.
* **post-mortem** (``--dumps``): merge flight-recorder crashdump
  directories (the :mod:`veles_tpu.telemetry.blackbox` loader) and
  synthesize pseudo-spans from the ``serve.*`` events carrying the
  trace id — works with every process dead.

Stdlib-only, jax-free, like the blackbox CLI: runs wherever the
artifact or the endpoint is reachable."""

import argparse
import json
import sys
import urllib.error
import urllib.request

from veles_tpu.telemetry import blackbox, tracing


def fetch_timeline(url, tid, timeout=10.0):
    """GET ``{url}/trace/{tid}`` -> the endpoint's JSON payload
    (router: merged + validated; replica: its local leg).  Raises
    OSError/ValueError on unreachable endpoints or non-JSON bodies."""
    target = "%s/trace/%s" % (url.rstrip("/"), tid)
    try:
        with urllib.request.urlopen(target, timeout=timeout) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        if e.code == 404:  # endpoint is fine, trace just unknown
            return {"spans": []}
        raise


def dump_timeline(dump_paths, tid):
    """Post-mortem reconstruction: pseudo-spans from every crashdump
    event carrying the trace id, merged across processes."""
    paths = blackbox.find_dumps(dump_paths)
    dumps = [blackbox.load_dump(d) for d in paths]
    events = blackbox.merge_timeline(dumps)
    return tracing.spans_from_flight(events, tid)


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="veles-tpu-trace",
        description="reconstruct one serving request's cross-process "
        "timeline by trace id, from live span-store endpoints or "
        "merged crashdumps")
    p.add_argument("trace", metavar="TRACE_ID",
                   help="the request's trace id (done-line/flight-"
                   "event 'trace' field)")
    p.add_argument("--url", default=None, metavar="URL",
                   help="live mode: base URL of a fleet router "
                   "(e.g. http://host:port/fleet) or replica "
                   "(http://host:port/api) — the CLI appends "
                   "/trace/<id>")
    p.add_argument("--dumps", nargs="+", default=None, metavar="DUMP",
                   help="post-mortem mode: crashdump-* directories "
                   "(or directories containing them); spans are "
                   "synthesized from the serve.* flight events")
    p.add_argument("--format", choices=("text", "json"),
                   default="text",
                   help="json emits {trace, spans, phases, gapless, "
                   "problems} for scripting (the chaos gates)")
    args = p.parse_args(argv)

    if not tracing.valid_id(args.trace):
        print("veles-tpu-trace: %r is not a trace id" % args.trace,
              file=sys.stderr)
        return 2
    if bool(args.url) == bool(args.dumps):
        print("veles-tpu-trace: exactly one of --url / --dumps",
              file=sys.stderr)
        return 2

    if args.url:
        try:
            payload = fetch_timeline(args.url, args.trace)
        except (OSError, ValueError) as e:
            print("veles-tpu-trace: %s" % e, file=sys.stderr)
            return 2
        spans = payload.get("spans") or []
    else:
        try:
            spans = dump_timeline(args.dumps, args.trace)
        except (OSError, ValueError) as e:
            print("veles-tpu-trace: %s" % e, file=sys.stderr)
            return 2

    if not spans:
        print("veles-tpu-trace: no spans for %s" % args.trace,
              file=sys.stderr)
        return 1
    verdict = tracing.validate(spans)
    if args.format == "json":
        print(json.dumps(
            {"trace": args.trace, "spans": spans,
             "phases": tracing.phases_of(spans),
             "gapless": verdict["ok"],
             "problems": verdict["problems"]},
            indent=1, default=str))
    else:
        print(tracing.render_timeline(
            spans, title="trace %s (%d spans)"
            % (args.trace, len(spans))))
    return 0


if __name__ == "__main__":
    sys.exit(main())
