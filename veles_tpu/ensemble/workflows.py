"""Ensembles (ref: veles/ensemble/model_workflow.py:137,
test_workflow.py:102 — ``--ensemble-train N:ratio`` trains N instances on
random train subsets with per-model seeds; ``--ensemble-test`` aggregates
their predictions).

Host-level orchestration, like the reference (each instance is a full
training run); results aggregate as JSON-able dicts and test-time
prediction averages."""

import numpy as np

from veles_tpu import prng
from veles_tpu.logger import Logger


class EnsembleTrainer(Logger):
    """Train N model instances on random train-subsets.

    :param build: callable(instance_index, train_indices) returning a
        2-tuple ``(model, result_dict)`` — ``model`` is whatever the caller
        wants collected (e.g. a predict function or a trained workflow),
        ``result_dict`` is JSON-able metadata aggregated into results.
    :param n_models: N; ``train_ratio``: fraction of train set per model.
    """

    def __init__(self, build, n_train_samples, n_models=4, train_ratio=0.8,
                 rng_name="ensemble"):
        super(EnsembleTrainer, self).__init__()
        self.build = build
        self.n_models = n_models
        self.train_ratio = train_ratio
        self.n_train_samples = n_train_samples
        self.rng = prng.get(rng_name)
        self.models = []
        self.results = []

    def run(self):
        n_sub = max(1, int(self.n_train_samples * self.train_ratio))
        for i in range(self.n_models):
            subset = np.sort(
                self.rng.numpy().choice(self.n_train_samples, n_sub,
                                        replace=False).astype(np.int64))
            self.info("training ensemble member %d/%d on %d samples",
                      i + 1, self.n_models, n_sub)
            model, result = self.build(i, subset)
            self.models.append(model)
            self.results.append(result)
        return self.models

    def get_metric_values(self):
        return {"ensemble": self.results}


class EnsembleTester(Logger):
    """Aggregate member predictions: mean of per-model probability outputs
    (ref EnsembleTestWorkflow result averaging)."""

    def __init__(self, predict_fns):
        super(EnsembleTester, self).__init__()
        self.predict_fns = list(predict_fns)
        if not self.predict_fns:
            raise ValueError("EnsembleTester needs at least one member")

    def predict(self, x):
        probs = None
        for fn in self.predict_fns:
            p = np.asarray(fn(x))
            probs = p if probs is None else probs + p
        return probs / len(self.predict_fns)

    def error_rate(self, x, labels):
        pred = self.predict(x).argmax(axis=1)
        return float((pred != np.asarray(labels)).mean())
