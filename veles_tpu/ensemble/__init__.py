"""Ensemble training/testing (ref: veles/ensemble/ — SURVEY §2.8)."""

from veles_tpu.ensemble.workflows import EnsembleTrainer, EnsembleTester

__all__ = ["EnsembleTrainer", "EnsembleTester"]
