"""Class-scoped logging with colored console output and structured event
records (ref: veles/logger.py:59-331).

Every framework object mixes in :class:`Logger` and gets a per-class logger
plus :meth:`Logger.event` — structured begin/end/single trace records used for
the event timeline (the reference shipped them to MongoDB `veles.events`,
logger.py:264-289; here they go to an in-process ring buffer and optionally a
JSON-lines file, browsable by the web-status service)."""

import json
import logging
import os
import sys
import threading
import time


class TerminalFormatter(logging.Formatter):
    """ANSI color formatter (ref veles/logger.py:123-160)."""

    COLORS = {
        logging.DEBUG: "\033[1;37m",
        logging.INFO: "\033[1;32m",
        logging.WARNING: "\033[1;33m",
        logging.ERROR: "\033[1;31m",
        logging.CRITICAL: "\033[1;35m",
    }
    RESET = "\033[0m"

    def __init__(self, colorize=None):
        super(TerminalFormatter, self).__init__(
            "%(asctime)s %(levelname).1s %(name)s: %(message)s", "%H:%M:%S")
        if colorize is None:
            colorize = hasattr(sys.stdout, "isatty") and sys.stdout.isatty()
        self._colorize = colorize

    def format(self, record):
        msg = super(TerminalFormatter, self).format(record)
        if self._colorize:
            color = self.COLORS.get(record.levelno)
            if color:
                msg = color + msg + self.RESET
        return msg


class EventStore(object):
    """Ring buffer + optional JSONL sink for structured trace events."""

    def __init__(self, capacity=65536):
        self._events = []
        self._capacity = capacity
        self._lock = threading.Lock()
        self._sink = None

    def open_sink(self, path):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._sink = open(path, "a")

    def add(self, event):
        with self._lock:
            self._events.append(event)
            if len(self._events) > self._capacity:
                del self._events[:self._capacity // 2]
            if self._sink is not None:
                self._sink.write(json.dumps(event) + "\n")
                self._sink.flush()

    def snapshot(self):
        with self._lock:
            return list(self._events)

    def clear(self):
        with self._lock:
            self._events.clear()


#: process-global event store (the reference used one Mongo session per run)
events = EventStore()

_setup_done = False


def setup_logging(level=logging.INFO, filename=None):
    """Install the console handler once (ref veles/logger.py:86-121)."""
    global _setup_done
    rootlog = logging.getLogger()
    if not _setup_done:
        handler = logging.StreamHandler(sys.stdout)
        handler.setFormatter(TerminalFormatter())
        rootlog.addHandler(handler)
        _setup_done = True
    rootlog.setLevel(level)
    if filename:
        path = os.path.abspath(filename)
        for h in rootlog.handlers:
            if isinstance(h, logging.FileHandler) and h.baseFilename == path:
                return  # already attached — don't duplicate lines
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fh = logging.FileHandler(path)
        fh.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname).1s %(name)s: %(message)s"))
        rootlog.addHandler(fh)


#: filesystem types where SQLite WAL is unsupported (WAL needs a
#: coherent shared-memory file, which network filesystems don't give —
#: sqlite.org/wal.html §"WAL does not work over a network filesystem")
#: matched against the fstype with any "fuse." prefix stripped first:
#: network filesystems served through FUSE (fuse.glusterfs, fuse.sshfs,
#: fuse.s3fs ...) classify by their backend, while purely-local FUSE
#: mounts (fuseblk/ntfs-3g, encfs, bindfs) stay local
_NETWORK_FS = ("nfs", "cifs", "smb", "9p", "lustre", "gluster",
               "ceph", "beegfs", "gpfs", "afs", "sshfs", "s3fs",
               "davfs", "webdav")


def _network_fs_type(path):
    """Filesystem type backing ``path`` if it looks network-mounted,
    else None (best-effort longest-prefix match over /proc/mounts)."""
    try:
        best, fstype = "", None
        with open("/proc/mounts") as f:
            for line in f:
                parts = line.split()
                if len(parts) < 3:
                    continue
                mnt = parts[1].rstrip("/") or "/"
                # component boundary: /data must not claim /database
                if (path == mnt or path.startswith(mnt + "/")
                        or mnt == "/") and len(mnt) > len(best):
                    best, fstype = mnt, parts[2]
        if fstype:
            base = fstype.lower()
            if base.startswith("fuse."):
                base = base[len("fuse."):]
            if base.startswith(_NETWORK_FS):
                return fstype
    except OSError:
        pass
    return None


class SqliteLogHandler(logging.Handler):
    """Cross-run log duplication — the reference's MongoLogHandler
    (ref veles/logger.py:292-331: every record lands in a queryable
    store keyed by session + node, feeding the cross-run log browser)
    redesigned for a TPU pod: stdlib sqlite instead of a Mongo
    deployment, so one file on shared storage collects every run's
    logs with zero extra services.  Local paths get WAL; paths on a
    network filesystem (where WAL's shared-memory file is unsupported
    and risks corruption with multiple hosts appending) fall back to
    the rollback journal with busy-retry — the ``session``/``node``
    columns already disambiguate writers either way.  Query via
    :func:`search_logs` / :func:`log_sessions`, the dashboard's
    ``/api/logs``, or plain ``sqlite3``."""

    def __init__(self, path, session=None, node=None,
                 level=logging.NOTSET):
        super(SqliteLogHandler, self).__init__(level)
        import sqlite3
        self.path = os.path.abspath(path)
        self.session = session or time.strftime("run-%Y%m%d-%H%M%S")
        self.node = node if node is not None else os.getpid()
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        # one connection guarded by a lock: log records arrive from the
        # scheduler, service threads, and signal-adjacent paths alike
        self._conn = sqlite3.connect(self.path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._conn.execute("PRAGMA busy_timeout=5000")
            netfs = _network_fs_type(self.path)
            if netfs:
                self._conn.execute("PRAGMA journal_mode=DELETE")
            else:
                self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS logs ("
                "session TEXT, node TEXT, ts REAL, level TEXT, "
                "logger TEXT, pathname TEXT, lineno INTEGER, "
                "message TEXT)")
            self._conn.execute(
                "CREATE INDEX IF NOT EXISTS logs_session_ts "
                "ON logs (session, ts)")
            self._conn.commit()

    def emit(self, record):
        try:
            msg = record.getMessage()
            if record.exc_info:
                msg += "\n" + self.format(record).split(msg, 1)[-1]
            with self._lock:
                self._conn.execute(
                    "INSERT INTO logs VALUES (?,?,?,?,?,?,?,?)",
                    (self.session, str(self.node), record.created,
                     record.levelname, record.name, record.pathname,
                     record.lineno, msg))
                self._conn.commit()
        except Exception:   # noqa: BLE001 — logging must never raise
            self.handleError(record)

    def close(self):
        with self._lock:
            try:
                self._conn.commit()
                self._conn.close()
            except Exception:   # noqa: BLE001
                pass
        super(SqliteLogHandler, self).close()


def duplicate_log_to(path, session=None, node=None):
    """Attach a :class:`SqliteLogHandler` to the root logger (the
    reference's ``--log-mongo`` duplication, redesigned onto sqlite).
    Returns the handler; its ``.session`` is the run's browse key."""
    handler = SqliteLogHandler(path, session=session, node=node)
    logging.getLogger().addHandler(handler)
    return handler


def log_sessions(path):
    """The cross-run index: [{session, node_count, records, first, last}]
    newest first."""
    import sqlite3
    conn = sqlite3.connect(path)
    try:
        rows = conn.execute(
            "SELECT session, COUNT(DISTINCT node), COUNT(*), "
            "MIN(ts), MAX(ts) FROM logs GROUP BY session "
            "ORDER BY MIN(ts) DESC").fetchall()
    finally:
        conn.close()
    return [{"session": s, "node_count": n, "records": c,
             "first": f, "last": l} for s, n, c, f, l in rows]


def search_logs(path, session=None, q=None, level=None, limit=500):
    """Search across runs: substring ``q`` on the message, optional
    session/level filters, newest first (the reference log browser's
    query surface, ref web_status log search)."""
    import sqlite3
    sql = ("SELECT session, node, ts, level, logger, pathname, lineno, "
           "message FROM logs WHERE 1=1")
    params = []
    if session:
        sql += " AND session = ?"
        params.append(session)
    if level:
        sql += " AND level = ?"
        params.append(level.upper())
    if q:
        sql += " AND message LIKE ?"
        params.append("%" + q + "%")
    sql += " ORDER BY ts DESC LIMIT ?"
    params.append(int(limit))
    conn = sqlite3.connect(path)
    try:
        rows = conn.execute(sql, params).fetchall()
    finally:
        conn.close()
    keys = ("session", "node", "ts", "level", "logger", "pathname",
            "lineno", "message")
    return [dict(zip(keys, r)) for r in rows]


class Logger(object):
    """Mixin giving every object a class-scoped logger (ref logger.py:59)."""

    def __init__(self, **kwargs):
        super(Logger, self).__init__()
        self._logger_ = logging.getLogger(type(self).__name__)

    @property
    def logger(self):
        if not hasattr(self, "_logger_"):
            self._logger_ = logging.getLogger(type(self).__name__)
        return self._logger_

    def debug(self, msg, *args):
        self.logger.debug(msg, *args)

    def info(self, msg, *args):
        self.logger.info(msg, *args)

    def warning(self, msg, *args):
        self.logger.warning(msg, *args)

    def error(self, msg, *args):
        self.logger.error(msg, *args)

    def exception(self, msg="", *args):
        self.logger.exception(msg, *args)

    def event(self, name, etype, **info):
        """Record a structured trace event (ref veles/logger.py:264-289).

        :param etype: "begin" | "end" | "single"
        """
        if etype not in ("begin", "end", "single"):
            raise ValueError("etype must be begin/end/single, got %r" % etype)
        record = {"name": name, "cat": type(self).__name__, "type": etype,
                  "time": time.time()}
        record.update(info)
        events.add(record)
        return record
