"""Class-scoped logging with colored console output and structured event
records (ref: veles/logger.py:59-331).

Every framework object mixes in :class:`Logger` and gets a per-class logger
plus :meth:`Logger.event` — structured begin/end/single trace records used for
the event timeline (the reference shipped them to MongoDB `veles.events`,
logger.py:264-289; here they go to an in-process ring buffer and optionally a
JSON-lines file, browsable by the web-status service)."""

import json
import logging
import os
import sys
import threading
import time


class TerminalFormatter(logging.Formatter):
    """ANSI color formatter (ref veles/logger.py:123-160)."""

    COLORS = {
        logging.DEBUG: "\033[1;37m",
        logging.INFO: "\033[1;32m",
        logging.WARNING: "\033[1;33m",
        logging.ERROR: "\033[1;31m",
        logging.CRITICAL: "\033[1;35m",
    }
    RESET = "\033[0m"

    def __init__(self, colorize=None):
        super(TerminalFormatter, self).__init__(
            "%(asctime)s %(levelname).1s %(name)s: %(message)s", "%H:%M:%S")
        if colorize is None:
            colorize = hasattr(sys.stdout, "isatty") and sys.stdout.isatty()
        self._colorize = colorize

    def format(self, record):
        msg = super(TerminalFormatter, self).format(record)
        if self._colorize:
            color = self.COLORS.get(record.levelno)
            if color:
                msg = color + msg + self.RESET
        return msg


class EventStore(object):
    """Ring buffer + optional JSONL sink for structured trace events."""

    def __init__(self, capacity=65536):
        self._events = []
        self._capacity = capacity
        self._lock = threading.Lock()
        self._sink = None

    def open_sink(self, path):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._sink = open(path, "a")

    def add(self, event):
        with self._lock:
            self._events.append(event)
            if len(self._events) > self._capacity:
                del self._events[:self._capacity // 2]
            if self._sink is not None:
                self._sink.write(json.dumps(event) + "\n")
                self._sink.flush()

    def snapshot(self):
        with self._lock:
            return list(self._events)

    def clear(self):
        with self._lock:
            self._events.clear()


#: process-global event store (the reference used one Mongo session per run)
events = EventStore()

_setup_done = False


def setup_logging(level=logging.INFO, filename=None):
    """Install the console handler once (ref veles/logger.py:86-121)."""
    global _setup_done
    rootlog = logging.getLogger()
    if not _setup_done:
        handler = logging.StreamHandler(sys.stdout)
        handler.setFormatter(TerminalFormatter())
        rootlog.addHandler(handler)
        _setup_done = True
    rootlog.setLevel(level)
    if filename:
        path = os.path.abspath(filename)
        for h in rootlog.handlers:
            if isinstance(h, logging.FileHandler) and h.baseFilename == path:
                return  # already attached — don't duplicate lines
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fh = logging.FileHandler(path)
        fh.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname).1s %(name)s: %(message)s"))
        rootlog.addHandler(fh)


class Logger(object):
    """Mixin giving every object a class-scoped logger (ref logger.py:59)."""

    def __init__(self, **kwargs):
        super(Logger, self).__init__()
        self._logger_ = logging.getLogger(type(self).__name__)

    @property
    def logger(self):
        if not hasattr(self, "_logger_"):
            self._logger_ = logging.getLogger(type(self).__name__)
        return self._logger_

    def debug(self, msg, *args):
        self.logger.debug(msg, *args)

    def info(self, msg, *args):
        self.logger.info(msg, *args)

    def warning(self, msg, *args):
        self.logger.warning(msg, *args)

    def error(self, msg, *args):
        self.logger.error(msg, *args)

    def exception(self, msg="", *args):
        self.logger.exception(msg, *args)

    def event(self, name, etype, **info):
        """Record a structured trace event (ref veles/logger.py:264-289).

        :param etype: "begin" | "end" | "single"
        """
        if etype not in ("begin", "end", "single"):
            raise ValueError("etype must be begin/end/single, got %r" % etype)
        record = {"name": name, "cat": type(self).__name__, "type": etype,
                  "time": time.time()}
        record.update(info)
        events.add(record)
        return record
