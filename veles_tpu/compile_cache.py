"""Persistent XLA compilation cache — compile once per chip window.

On the tunneled single-chip setup, first-compile latency (~20-40 s per
jitted program) is paid out of the scarcest budget this repo has: TPU
uptime.  ``bench.py`` runs every phase in its own subprocess, so without
a persistent cache each phase recompiles its programs from scratch even
inside one window, and the driver's end-of-round bench recompiles
everything a previous window already compiled.  Pointing JAX's
persistent compilation cache at an on-disk directory makes compiled
executables survive process boundaries: the second run of any phase in
a window — and the driver's end-of-round capture after a watcher-fired
one — skips straight to measurement.

This is the same economics as the reference's on-disk kernel cache
(ref ``veles/accelerated_units.py`` caches built OpenCL/CUDA program
binaries keyed by source+options so re-runs skip compilation); here the
unit of caching is the whole XLA executable, keyed by JAX on
(HLO, compile options, compiler version, device kind), so a cache
written against one backend can never be served to another.

Usage::

    from veles_tpu import compile_cache
    compile_cache.enable()            # default: <repo>/.xla_cache
    compile_cache.enable("/fast/ssd") # explicit location

Environment: ``VELES_COMPILE_CACHE`` overrides the default directory
(relative paths are absolutized at read time); ``=1/on/true/yes``
keeps the default directory; ``=0/off/false/no`` disables enable()
entirely — the escape hatch for read-only filesystems.

Known cosmetic noise: on CPU cache *hits*, XLA's AOT loader logs
E-level "machine type ... doesn't match" lines because the compile-time
feature list includes XLA-internal pseudo-features (prefer-no-scatter/
-gather) that host detection never reports.  Same-host reloads are
safe (verified end-to-end: a cached digits-MLP run reproduces the
fresh-compile results exactly); the TPU executable path does not use
that loader.
"""

import os

#: min seconds of compile time before an executable is persisted.  0.0
#: persists everything: on this setup even "cheap" compiles cost a
#: tunnel round-trip to re-do, and the cache directory is repo-local
#: scratch, so disk is cheaper than uptime.
_MIN_COMPILE_SECS = 0.0

_enabled_dir = None
_metrics_installed = False


def install_metrics():
    """Subscribe compile count/time to the telemetry registry via jax's
    monitoring hooks: every ``/jax/core/compile/*`` duration event feeds
    ``veles_compile_events_total`` / ``veles_compile_seconds_total``
    (labeled by the event's short name), and the compilation-cache
    events (hits, cache-enabled requests) feed
    ``veles_compile_cache_events_total`` — so a run's metrics JSONL
    carries exactly how much wall time recompilation cost and how often
    this module's persistent cache saved it.  Idempotent; returns False
    when jax's monitoring internals moved (telemetry is best-effort,
    the framework must still start)."""
    global _metrics_installed
    if _metrics_installed:
        return True
    try:
        from jax._src import monitoring
    except ImportError:
        return False

    def on_duration(event, duration, **kwargs):
        if "/compile/" not in event and not event.endswith("compile"):
            return
        # listeners fire inside jax's compile path: never raise
        try:
            from veles_tpu import telemetry
            key = event.rsplit("/", 1)[-1]
            reg = telemetry.registry
            reg.counter("veles_compile_events_total",
                        "jax compile-phase events", ("event",)).inc(
                event=key)
            reg.counter("veles_compile_seconds_total",
                        "seconds spent in jax compile phases",
                        ("event",)).inc(duration, event=key)
            # the black box wants compiles too: a post-mortem timeline
            # where the last event is a 40 s backend_compile explains a
            # "hang" that was really a recompile storm — and compiling
            # IS progress, so the hang watchdog must not trip on it
            telemetry.flight.record("compile", event=key,
                                    dur_s=duration)
            telemetry.health.note_progress()
        except Exception:   # noqa: BLE001
            pass

    def on_event(event, **kwargs):
        if "compilation_cache" not in event:
            return
        try:
            from veles_tpu import telemetry
            telemetry.registry.counter(
                "veles_compile_cache_events_total",
                "jax compilation-cache events (hits, cached requests)",
                ("event",)).inc(event=event.rsplit("/", 1)[-1])
        except Exception:   # noqa: BLE001
            pass

    try:
        monitoring.register_event_duration_secs_listener(on_duration)
        monitoring.register_event_listener(on_event)
    except Exception:   # noqa: BLE001 — monitoring API moved
        return False
    _metrics_installed = True
    return True


def _accelerator_evidence():
    """Cheap accelerator sniff WITHOUT initializing a jax backend:
    TPU device nodes or the libtpu runtime, or NVIDIA device nodes.
    Erring toward True only re-enables the old default (cache on)."""
    import glob
    import importlib.util
    if glob.glob("/dev/accel*") or glob.glob("/dev/nvidia*"):
        return True
    try:
        return importlib.util.find_spec("libtpu") is not None
    except (ImportError, ValueError):
        return False


def _cpu_backend():
    """True when the run will land on the CPU backend: explicitly
    pinned there (config flag or ``JAX_PLATFORMS``), or nothing pinned
    and no accelerator evidence on the machine — jax auto-selects CPU
    there, so an unpinned CPU-only run must decline the cache the same
    way a pinned one does.  Read WITHOUT initializing the backend."""
    import jax
    try:
        platforms = str(jax.config.jax_platforms
                        or os.environ.get("JAX_PLATFORMS", ""))
    except AttributeError:
        platforms = os.environ.get("JAX_PLATFORMS", "")
    first = platforms.split(",")[0].strip().lower()
    if first:
        return first == "cpu"
    return not _accelerator_evidence()


def default_dir():
    """Repo-local scratch: survives process restarts within a round and
    is visible to the driver's end-of-round ``bench.py`` run."""
    env = os.environ.get("VELES_COMPILE_CACHE", "")
    # boolean-intent values mean on/off, never a directory literally
    # named "1"; explicit paths are absolutized so processes launched
    # from different cwds (driver vs bench phase children) share ONE
    # cache — the whole point of the module
    if env and env.lower() not in ("0", "off", "false", "no",
                                   "1", "on", "true", "yes"):
        return os.path.abspath(env)
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".xla_cache")


def enable(path=None):
    """Point JAX's persistent compilation cache at *path* (created if
    missing).  Idempotent; returns the directory in use, or None when
    disabled via env / unsupported by this JAX build.

    Safe to call before or after backend init — JAX reads the config at
    compile time, not import time.  Never raises: a framework must not
    fail to start because a cache knob moved between JAX versions, so
    unknown option names are skipped individually.
    """
    global _enabled_dir
    # compile telemetry is independent of the on-disk cache: count
    # compiles even when the env disables persistence below
    install_metrics()
    env = os.environ.get("VELES_COMPILE_CACHE", "")
    if env.lower() in ("0", "off", "false", "no"):
        return None
    if path is None and not env and _cpu_backend():
        # the automatic default stays OFF on the CPU backend: XLA:CPU
        # executable DESERIALIZATION is unreliable in sandboxed/old-
        # kernel environments (glibc heap corruption — measured ~40%
        # of digits-MLP runs die by SIGSEGV/SIGABRT with the cache on,
        # 0% with it off; this was ROADMAP's "known environment
        # flake"), and a CPU compile costs seconds where a TPU
        # recompile costs minutes.  An explicit ``path=`` argument or
        # a VELES_COMPILE_CACHE directory still opts in on any
        # backend.
        return None
    if path is None:
        path = default_dir()
    try:
        os.makedirs(path, exist_ok=True)
    except OSError:
        return None
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir", path)
    except (AttributeError, ValueError):
        return None          # core option gone: caching is NOT active
    for opt, val in (
            ("jax_persistent_cache_min_compile_time_secs",
             _MIN_COMPILE_SECS),
            ("jax_persistent_cache_min_entry_size_bytes", 0),
            # also persist XLA-level autotune/kernel caches where the
            # backend supports it (no-op elsewhere)
            ("jax_persistent_cache_enable_xla_caches", "all"),
    ):
        try:
            jax.config.update(opt, val)
        except (AttributeError, ValueError):
            pass
    # jax latches its cache singleton (and a cache-unused verdict) at the
    # process's FIRST compile; enabling — or re-pointing — after any
    # compile has happened would otherwise be a silent no-op.  Reset the
    # latch so the next compile re-initializes against the new directory.
    try:
        from jax._src import compilation_cache as _jax_cc
        if getattr(_jax_cc, "_cache_initialized", False) \
                or getattr(_jax_cc, "_cache_checked", False):
            _jax_cc.reset_cache()
    except Exception:  # noqa: BLE001 — internals moved: stay best-effort
        pass
    _enabled_dir = path
    return path


def enabled_dir():
    """Directory the cache was enabled at this process, or None."""
    return _enabled_dir
