"""Services layer (ref SURVEY.md §2.7): snapshotter, result providers,
plotting, web status, RESTful serving, package export."""
