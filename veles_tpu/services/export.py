"""Workflow package export (ref: Workflow.package_export,
veles/workflow.py:864-971 — writes an archive of ``contents.json`` +
``.npy`` weight arrays that the native C++ runtime loads, mirroring the
libVeles contract `libVeles/src/main_file_loader.h:108-115` and its
round-trip test fixtures).

The archive is a ZIP with STORED (uncompressed) entries so the native
loader can parse it with ~100 lines of code instead of libarchive."""

import io
import json
import os
import zipfile

import numpy as np

from veles_tpu import __version__


def unflatten_params(flat):
    """Inverse of the export-side flattening: {"gn1/gamma": a} →
    {"gn1": {"gamma": a}} — consumers rebuilding live param trees from
    a package (ensemble vote, warm starts from packages) need the
    NESTED layout composite layers' apply() indexes."""
    out = {}
    for key, v in flat.items():
        node = out
        parts = key.split("/")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = v
    return out


def _flatten_params(sub, prefix=""):
    """Composite layers (conv_residual_block, transformer_block) keep
    NESTED param dicts; the package format stores one flat array map per
    unit with "/"-joined names ("gn1/gamma")."""
    out = {}
    for k, v in sub.items():
        key = "%s/%s" % (prefix, k) if prefix else k
        if isinstance(v, dict):
            out.update(_flatten_params(v, key))
        else:
            out[key] = v
    return out


def export_workflow(workflow, path, dtype="float32"):
    """Write a StandardWorkflow-style trained model to ``path`` (.zip).

    contents.json schema:
      {"name", "framework", "version", "loss", "input_shape",
       "units": [{"name", "type", "config", "input_shape", "output_shape",
                  "arrays": {"weights": "file.npy", ...}}, ...]}

    ``dtype="float16"`` halves the package: weights are stored <f2 and
    the native runtime widens them to f32 on load (the reference's
    optional fp16→fp32 transform, libVeles numpy_array_loader.cc).
    ``dtype="int8"`` quarters it: >= 2-D float arrays store symmetric
    per-output-channel int8 (``<i1`` + a ``<f4`` "<param>__scales"
    companion, one scale per last-dim column); biases and 1-D arrays
    stay f32.  Both the native runtime and import_workflow dequantize
    on load."""
    if dtype not in ("float32", "float16", "int8"):
        raise ValueError("dtype must be float32, float16 or int8")
    trainer = workflow.trainer
    host = trainer.host_params()
    units = []
    files = {}
    for i, layer in enumerate(trainer.layers):
        arrays = {}
        for pname, arr in _flatten_params(
                host.get(layer.name) or {}).items():
            arr = np.asarray(arr)
            fname = "%04d_%s_%s.npy" % (i, layer.name,
                                        pname.replace("/", "_"))
            arrays[pname] = fname
            if dtype == "int8" and arr.ndim >= 2 and _is_floating(arr):
                arrf = arr.astype(np.float32)   # incl. ml_dtypes bf16
                scales = np.maximum(
                    np.abs(arrf).max(axis=tuple(range(arrf.ndim - 1))),
                    1e-8).astype(np.float32) / 127.0
                files[fname] = np.clip(
                    np.round(arrf / scales), -127, 127).astype(np.int8)
                sname = fname[:-4] + "__scales.npy"
                arrays[pname + "__scales"] = sname
                files[sname] = scales
            else:
                files[fname] = arr
        cfg = {k: v for k, v in layer.cfg.items() if _jsonable(v)}
        units.append({
            "name": layer.name,
            "type": layer.type,
            "config": cfg,
            "input_shape": list(layer.input_shape or ()),
            "output_shape": list(layer.output_shape or ()),
            "arrays": arrays,
        })
    from veles_tpu.ops import losses as _losses
    manifest = {
        "name": workflow.name,
        "framework": "veles_tpu",
        "version": __version__,
        "loss": trainer.loss,
        # class-kind losses serve probabilities (forward_fn applies
        # softmax) — the native runtime branches on the KIND so plugin
        # losses keep the contract without a name allowlist
        "loss_kind": _losses.get_loss(trainer.loss)[1],
        "input_shape": list(trainer.layers[0].input_shape or ()),
        "units": units,
    }
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                exist_ok=True)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED) as zf:
        zf.writestr("contents.json", json.dumps(manifest, indent=2))
        for fname, arr in files.items():
            if dtype == "int8":
                # int8 payloads / f32 scales keep their own dtypes;
                # un-quantized floats (biases) stay f32
                out = (arr if arr.dtype in (np.int8, np.float32)
                       else np.ascontiguousarray(arr, np.float32))
            else:
                out = np.ascontiguousarray(arr, dtype=dtype)
            buf = io.BytesIO()
            np.save(buf, out)
            zf.writestr(fname, buf.getvalue())
    return path


def import_workflow(path):
    """Read a package back into (manifest, {filename: array}) — the Python
    side of the round-trip test (ref libVeles tests load the same
    fixtures).  int8 payloads dequantize transparently (the "__scales"
    companions are folded in and dropped), so every consumer sees float
    arrays regardless of the export dtype."""
    with zipfile.ZipFile(path) as zf:
        manifest = json.loads(zf.read("contents.json"))
        arrays = {}
        for unit in manifest["units"]:
            for pname, fname in unit["arrays"].items():
                arrays[fname] = np.load(io.BytesIO(zf.read(fname)))
        for unit in manifest["units"]:
            ua = unit["arrays"]
            for pname in [p for p in ua if p.endswith("__scales")]:
                base = pname[: -len("__scales")]
                arrays[ua[base]] = (
                    arrays[ua[base]].astype(np.float32)
                    * arrays.pop(ua[pname]))
                del ua[pname]
    return manifest, arrays


def export_stablehlo(workflow, path, platforms=None):
    """Portable COMPILED serving artifact: the jitted forward serialized
    as StableHLO (``jax.export``) plus the trained params, in one ZIP —
    loadable on any machine with jax for the named platforms WITHOUT the
    model-building Python code.  Where the ``contents.json`` package
    (export_workflow) feeds the native C++ CPU runtime, this is the
    XLA-native sibling: one artifact, every XLA backend.  The batch dim
    is exported symbolically, so a single artifact serves any batch
    size.

    Package layout: ``model.stablehlo`` (versioned serialized bytes),
    ``params.npz`` ("layer/param"-keyed), ``meta.json``."""
    import jax
    from jax import export as jexport

    trainer = workflow.trainer
    host = trainer.host_params()
    in_shape = tuple(trainer.layers[0].input_shape)
    (b,) = jexport.symbolic_shape("b")
    # int-token models export with int32 inputs.  The model's own
    # first layer is the public contract (an embedding consumes token
    # ids) — loader-independent, unlike sniffing any loader's buffers.
    in_dtype = (np.int32 if trainer.layers[0].type == "embedding"
                else np.float32)
    x_spec = jax.ShapeDtypeStruct((b,) + in_shape, in_dtype)
    p_spec = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype),
        host)
    fwd = workflow.forward_fn()
    default = platforms is None
    if default:
        platforms = ("cpu", "tpu")
    try:
        exp = jexport.export(fwd, platforms=list(platforms))(p_spec,
                                                             x_spec)
    except Exception as e:  # noqa: BLE001 — e.g. a kernel with no
        # lowering for one platform of the DEFAULT set; an explicitly
        # requested platform list is a contract and failures surface
        if not default:
            raise
        import logging
        logging.getLogger("Export").warning(
            "multi-platform StableHLO export failed (%s: %s) — "
            "retrying cpu-only", type(e).__name__, e)
        platforms = ("cpu",)
        exp = jexport.export(fwd, platforms=["cpu"])(p_spec, x_spec)

    flat, _ = jax.tree_util.tree_flatten_with_path(host)
    buf = io.BytesIO()
    np.savez(buf, **{"/".join(str(k.key) for k in kpath):
                     np.asarray(arr) for kpath, arr in flat})
    meta = {"name": workflow.name, "framework": "veles_tpu",
            "version": __version__, "input_shape": list(in_shape),
            "input_dtype": np.dtype(in_dtype).name,
            "platforms": list(platforms)}
    with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED) as zf:
        zf.writestr("model.stablehlo", exp.serialize())
        zf.writestr("params.npz", buf.getvalue())
        zf.writestr("meta.json", json.dumps(meta, indent=1))
    return meta


def load_stablehlo(path):
    """Load an export_stablehlo package → ``(fn, meta)`` where ``fn(x)``
    runs the exported forward with the packaged params on the current
    default jax platform (which must be in ``meta['platforms']``)."""
    import jax
    from jax import export as jexport

    with zipfile.ZipFile(path) as zf:
        exp = jexport.deserialize(zf.read("model.stablehlo"))
        meta = json.loads(zf.read("meta.json"))
        npz = np.load(io.BytesIO(zf.read("params.npz")))
        params = {}
        for key in npz.files:          # "layer/.../param" → nested dicts
            node, parts = params, key.split("/")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = npz[key]

    in_dtype = np.dtype(meta.get("input_dtype", "float32"))

    def fn(x):
        return exp.call(params, jax.numpy.asarray(x, in_dtype))

    return fn, meta


def _is_floating(arr):
    """True for numpy floats AND ml_dtypes extensions (bfloat16 params
    from a custom precision policy have dtype kind 'V', which
    np.issubdtype does not classify as floating)."""
    if np.issubdtype(arr.dtype, np.floating):
        return True
    try:
        import ml_dtypes
        return arr.dtype == np.dtype(ml_dtypes.bfloat16)
    except ImportError:      # pragma: no cover — ships with jax
        return False


def _jsonable(v):
    try:
        json.dumps(v)
        return True
    except (TypeError, ValueError):
        return False


# --------------------------------------------------------------- LoRA
def _base_sha256(host_params):
    """Digest of every NON-adapter leaf (key + bytes, tree order) —
    the lineage identity both export_lora_adapters and
    apply_lora_adapters must compute identically."""
    import hashlib

    import jax

    sha = hashlib.sha256()
    flat, _ = jax.tree_util.tree_flatten_with_path(host_params)
    for kpath, arr in flat:
        key = "/".join(str(k.key) for k in kpath)
        if "lora" not in key:
            sha.update(key.encode())
            sha.update(np.ascontiguousarray(arr).tobytes())
    return sha.hexdigest()


def _lora_subtrees(host_params):
    """{layer_name: lora_dict} for every layer carrying adapters —
    both the transformer blocks' ``mha/lora`` subtree and the dense
    layers' flat ``lora_a``/``lora_b`` pairs."""
    out = {}
    for lname, sub in host_params.items():
        if not isinstance(sub, dict):
            continue
        if isinstance(sub.get("mha"), dict) and "lora" in sub["mha"]:
            out[lname] = {"mha/lora/" + k: np.asarray(v)
                          for k, v in sub["mha"]["lora"].items()}
        flat = {k: np.asarray(v) for k, v in sub.items()
                if k.startswith("lora_")}
        if flat:
            out.setdefault(lname, {}).update(flat)
    return out


def export_lora_adapters(workflow, path):
    """Ship ONLY the adapters as a package: ``adapters.npz`` keyed
    "layer/mha/lora/qa" + ``meta.json`` carrying the base model's
    param sha256 so a serving host can refuse adapters trained against
    a different base (the Forge manifest-lineage idea applied to
    fine-tunes).  A 124M GPT-2-class base with rank-8 q/v adapters
    ships ~1.6 MB instead of ~500 MB."""
    host = workflow.trainer.host_params()
    subtrees = _lora_subtrees(host)
    if not subtrees:
        raise ValueError("workflow has no LoRA adapters to export "
                         "(train with lora_rank > 0)")
    buf = io.BytesIO()
    np.savez(buf, **{lname + "/" + k: v
                     for lname, sub in subtrees.items()
                     for k, v in sub.items()})
    meta = {"name": workflow.name, "framework": "veles_tpu",
            "version": __version__, "kind": "lora_adapters",
            "base_sha256": _base_sha256(host),
            "layers": sorted(subtrees)}
    with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED) as zf:
        zf.writestr("adapters.npz", buf.getvalue())
        zf.writestr("meta.json", json.dumps(meta, indent=1))
    return meta


def load_lora_adapters(path):
    """Load an adapters package → (nested adapter tree, meta).  Apply
    with ``apply_lora_adapters`` to a compatible base workflow."""
    with zipfile.ZipFile(path) as zf:
        meta = json.loads(zf.read("meta.json"))
        if meta.get("kind") != "lora_adapters":
            raise ValueError("%s is not a LoRA adapters package" % path)
        npz = np.load(io.BytesIO(zf.read("adapters.npz")))
        return unflatten_params({k: npz[k] for k in npz.files}), meta


def merge_lora_params(host_params):
    """Fold the adapters into the base weights — Wq ← Wq + qa·qb,
    Wv ← Wv + va·vb, dense W ← W + lora_a·lora_b — and DROP the lora
    subtrees: the merged model serves/exports with zero adapter
    overhead (one matmul per projection again) and bit-identical f32
    outputs, since the adapted forward computes exactly
    x·W + (x·A)·B = x·(W + A·B).  Returns a new host tree."""
    out = {}
    for lname, sub in host_params.items():
        if not isinstance(sub, dict):
            out[lname] = sub
            continue
        sub = dict(sub)
        if isinstance(sub.get("mha"), dict) and "lora" in sub["mha"]:
            mha = dict(sub["mha"])
            lora = mha.pop("lora")
            for wk, ak, bk in (("wq", "qa", "qb"), ("wv", "va", "vb")):
                if ak in lora:
                    w = np.asarray(mha[wk], np.float32)
                    d = np.asarray(lora[ak], np.float32) @ \
                        np.asarray(lora[bk], np.float32)
                    mha[wk] = (w + d).astype(np.asarray(mha[wk]).dtype)
            sub["mha"] = mha
        if "lora_a" in sub:
            w = np.asarray(sub["weights"], np.float32)
            d = np.asarray(sub.pop("lora_a"), np.float32) @ \
                np.asarray(sub.pop("lora_b"), np.float32)
            sub["weights"] = (w + d).astype(
                np.asarray(sub["weights"]).dtype)
        out[lname] = sub
    return out


def apply_lora_adapters(workflow, path, strict=True):
    """Graft an adapters package onto a live base workflow: verify the
    base-model sha256 lineage (``strict=False`` downgrades a mismatch
    to a warning — for intentionally cross-base experiments), then
    replace each carrying layer's lora subtree with the package's
    arrays.  The serving paths pick the adapters up immediately
    (attention._qkv_proj chokepoint)."""
    import logging

    tree, meta = load_lora_adapters(path)
    host = workflow.trainer.host_params()
    sha = _base_sha256(host)
    if sha != meta["base_sha256"]:
        msg = ("adapters package %s was trained against a different "
               "base model (sha %s... != %s...)"
               % (path, meta["base_sha256"][:12], sha[:12]))
        if strict:
            raise ValueError(msg)
        logging.getLogger("Export").warning(msg)
    params = {k: dict(v) if isinstance(v, dict) else v
              for k, v in host.items()}
    for lname, sub in tree.items():
        if lname not in params:
            raise ValueError("adapter layer %r not in this workflow"
                             % lname)
        if "mha" in sub:
            mha = dict(params[lname]["mha"])
            mha["lora"] = sub["mha"]["lora"]
            params[lname]["mha"] = mha
        for k, v in sub.items():
            if k.startswith("lora_"):
                params[lname][k] = v
    workflow.trainer.load_params(params)
    return meta
