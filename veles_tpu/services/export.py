"""Workflow package export (ref: Workflow.package_export,
veles/workflow.py:864-971 — writes an archive of ``contents.json`` +
``.npy`` weight arrays that the native C++ runtime loads, mirroring the
libVeles contract `libVeles/src/main_file_loader.h:108-115` and its
round-trip test fixtures).

The archive is a ZIP with STORED (uncompressed) entries so the native
loader can parse it with ~100 lines of code instead of libarchive."""

import io
import json
import os
import zipfile

import numpy as np

from veles_tpu import __version__


def export_workflow(workflow, path, dtype="float32"):
    """Write a StandardWorkflow-style trained model to ``path`` (.zip).

    contents.json schema:
      {"name", "framework", "version", "loss", "input_shape",
       "units": [{"name", "type", "config", "input_shape", "output_shape",
                  "arrays": {"weights": "file.npy", ...}}, ...]}

    ``dtype="float16"`` halves the package: weights are stored <f2 and
    the native runtime widens them to f32 on load (the reference's
    optional fp16→fp32 transform, libVeles numpy_array_loader.cc).
    ``dtype="int8"`` quarters it: >= 2-D float arrays store symmetric
    per-output-channel int8 (``<i1`` + a ``<f4`` "<param>__scales"
    companion, one scale per last-dim column); biases and 1-D arrays
    stay f32.  Both the native runtime and import_workflow dequantize
    on load."""
    if dtype not in ("float32", "float16", "int8"):
        raise ValueError("dtype must be float32, float16 or int8")
    trainer = workflow.trainer
    host = trainer.host_params()
    units = []
    files = {}
    for i, layer in enumerate(trainer.layers):
        arrays = {}
        for pname, arr in (host.get(layer.name) or {}).items():
            arr = np.asarray(arr)
            fname = "%04d_%s_%s.npy" % (i, layer.name, pname)
            arrays[pname] = fname
            if dtype == "int8" and arr.ndim >= 2 and _is_floating(arr):
                arrf = arr.astype(np.float32)   # incl. ml_dtypes bf16
                scales = np.maximum(
                    np.abs(arrf).max(axis=tuple(range(arrf.ndim - 1))),
                    1e-8).astype(np.float32) / 127.0
                files[fname] = np.clip(
                    np.round(arrf / scales), -127, 127).astype(np.int8)
                sname = fname[:-4] + "__scales.npy"
                arrays[pname + "__scales"] = sname
                files[sname] = scales
            else:
                files[fname] = arr
        cfg = {k: v for k, v in layer.cfg.items() if _jsonable(v)}
        units.append({
            "name": layer.name,
            "type": layer.type,
            "config": cfg,
            "input_shape": list(layer.input_shape or ()),
            "output_shape": list(layer.output_shape or ()),
            "arrays": arrays,
        })
    manifest = {
        "name": workflow.name,
        "framework": "veles_tpu",
        "version": __version__,
        "loss": trainer.loss,
        "input_shape": list(trainer.layers[0].input_shape or ()),
        "units": units,
    }
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                exist_ok=True)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED) as zf:
        zf.writestr("contents.json", json.dumps(manifest, indent=2))
        for fname, arr in files.items():
            if dtype == "int8":
                # int8 payloads / f32 scales keep their own dtypes;
                # un-quantized floats (biases) stay f32
                out = (arr if arr.dtype in (np.int8, np.float32)
                       else np.ascontiguousarray(arr, np.float32))
            else:
                out = np.ascontiguousarray(arr, dtype=dtype)
            buf = io.BytesIO()
            np.save(buf, out)
            zf.writestr(fname, buf.getvalue())
    return path


def import_workflow(path):
    """Read a package back into (manifest, {filename: array}) — the Python
    side of the round-trip test (ref libVeles tests load the same
    fixtures).  int8 payloads dequantize transparently (the "__scales"
    companions are folded in and dropped), so every consumer sees float
    arrays regardless of the export dtype."""
    with zipfile.ZipFile(path) as zf:
        manifest = json.loads(zf.read("contents.json"))
        arrays = {}
        for unit in manifest["units"]:
            for pname, fname in unit["arrays"].items():
                arrays[fname] = np.load(io.BytesIO(zf.read(fname)))
        for unit in manifest["units"]:
            ua = unit["arrays"]
            for pname in [p for p in ua if p.endswith("__scales")]:
                base = pname[: -len("__scales")]
                arrays[ua[base]] = (
                    arrays[ua[base]].astype(np.float32)
                    * arrays.pop(ua[pname]))
                del ua[pname]
    return manifest, arrays


def _is_floating(arr):
    """True for numpy floats AND ml_dtypes extensions (bfloat16 params
    from a custom precision policy have dtype kind 'V', which
    np.issubdtype does not classify as floating)."""
    if np.issubdtype(arr.dtype, np.floating):
        return True
    try:
        import ml_dtypes
        return arr.dtype == np.dtype(ml_dtypes.bfloat16)
    except ImportError:      # pragma: no cover — ships with jax
        return False


def _jsonable(v):
    try:
        json.dumps(v)
        return True
    except (TypeError, ValueError):
        return False
