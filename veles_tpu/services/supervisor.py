"""Supervisor — the training plane's respawn loop (the Veles paper's
*Launcher* role mapped onto checkpoint-restart).

The serving plane survives kills because PRs 6–7 put a router and a
drain state machine around every engine; the training plane's
equivalent is this: one parent process that spawns the training
command, watches how it dies, and respawns it so ``--snapshot auto``
resumes from the last committed checkpoint.  The pieces it composes
already exist — SIGTERM → graceful preemption checkpoint → exit 75
(``__main__``), the ``_current`` symlink + torn-checkpoint fallback,
and the flight-recorder crashdumps — this module is the policy that
makes them a *survival loop* instead of a manual runbook:

* **exit 0** — training finished; done.
* **exit 75** (EX_TEMPFAIL, graceful preemption) — respawn
  immediately, unbounded: preemptions are the *normal* lifecycle on
  scheduled TPU pods, and each one left a fresh checkpoint.
* **killed by signal** (SIGKILL — OOM killer, hard preemption) —
  respawn with exponential backoff; counts against the crash-loop
  window.
* **nonzero exit** — consult the newest crashdump the child left
  (``artifacts/crashdump-*``): a ``fault.injected`` event means the
  chaos drill killed it (respawn); an excepthook error gives the crash
  a *signature*, and ``deterministic_limit`` consecutive identical
  signatures with **zero checkpoint progress** give up early — a
  deterministic bug replays identically from the same checkpoint, and
  restarting it only burns the restart budget.
* **numerics valve** — a ``sentinel.giveup`` event in the crashdump
  (the numeric-fault sentinel's rung-3 escalation, services.sentinel)
  classifies the exit ``numerics:<kind>``: ``deterministic_limit``
  identical anomaly signatures give up with a diagnosis **regardless
  of checkpoint progress** — a diverging run commits plenty while its
  rollbacks replay, but identical divergence across lives is
  deterministic all the same.
* **crash-loop valve** — more than ``max_restarts`` bounded respawns
  (kills + faults + crashes; preemptions are exempt) inside
  ``window_seconds`` give up with the child's exit code.

Progress is measured on the snapshot directory: any respawn that
advanced a checkpoint resets the backoff and the deterministic-bug
counter — a run that keeps committing is *working*, however it keeps
dying.  Config: ``root.common.supervise.*``; CLI: ``--supervise``;
chaos gate: ``tools/train_chaos.py`` (docs/distributed_training.md
"Preemption-safe training")."""

import json
import logging
import os
import random
import signal
import subprocess
import sys
import threading
import time

from veles_tpu.config import root
from veles_tpu.telemetry import flight

#: EX_TEMPFAIL — the graceful-preemption exit code (__main__)
EX_TEMPFAIL = 75

#: abort-class signals XLA startup dies with in sandboxed environments
#: (ROADMAP "Known environment flake": the crash lands inside backend
#: init, before the program's first print)
STARTUP_FLAKE_SIGNALS = (signal.SIGSEGV, signal.SIGABRT, signal.SIGBUS,
                         signal.SIGILL)


# --------------------------------------------------------------- shared
# The pod master's per-host agents (services.podmaster) supervise the
# same training command with the same death taxonomy — the policy
# differs (pod-coordinated restarts vs the local loop below), the
# classification and backoff must not.  These module functions are that
# shared core.

def backoff_delay(attempt, base_s, max_s, rng):
    """Exponential backoff with jitter: base·2^(n-1) capped at max_s,
    scaled by [0.5, 1.0) — the fleet router's shape, shared by the
    single-host Supervisor and the pod master (test-pinned)."""
    d = min(base_s * (2 ** max(attempt - 1, 0)), max_s)
    return d * (0.5 + 0.5 * rng.random())


def read_crashdump(blackbox_dir, since):
    """(events, meta) of the newest crashdump written after ``since``,
    or ([], None).  ``since`` is the attempt's spawn time on the SAME
    clock that stamps the dump's mtime, so no slop is needed — and none
    is allowed: a previous attempt's dump lands between its exit and
    this spawn, and any slop window shorter backoffs can fit into would
    attribute that stale dump (and its signature) to the wrong death.
    Never raises — forensics inform the policy, they must not crash
    it."""
    try:
        newest, newest_ts = None, since
        for name in os.listdir(blackbox_dir):
            if not name.startswith("crashdump-") or ".tmp-" in name:
                continue
            path = os.path.join(blackbox_dir, name)
            ts = os.path.getmtime(path)
            if ts >= newest_ts:
                newest, newest_ts = path, ts
        if newest is None:
            return [], None
        events = []
        with open(os.path.join(newest, "events.jsonl")) as f:
            for line in f:
                try:
                    events.append(json.loads(line))
                except ValueError:
                    continue
        meta = None
        try:
            with open(os.path.join(newest, "meta.json")) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            pass
        return events, meta
    except OSError:
        return [], None


def classify_exit(rc, blackbox_dir=None, since=0.0):
    """(kind, crash_signature) for one child exit — the crashdump the
    child left behind distinguishes an injected/forced death from a
    deterministic bug.  Kinds: ``done``, ``preempt`` (exit 75),
    ``killed:SIG*`` (negative rc), ``fault-injection`` (crashdump
    carries a ``fault.injected`` event), ``numerics:<kind>`` (the
    sentinel's rung-3 escalation — a ``sentinel.giveup`` event with a
    stable anomaly signature, services.sentinel), ``crash:<Type>`` /
    ``crash:rcN`` (signature set)."""
    if rc == 0:
        return "done", None
    if rc == EX_TEMPFAIL:
        return "preempt", None
    if rc < 0:
        try:
            name = signal.Signals(-rc).name
        except ValueError:
            name = "SIG%d" % -rc
        return "killed:%s" % name, None
    events, meta = ([], None) if blackbox_dir is None else \
        read_crashdump(blackbox_dir, since)
    for ev in events:
        if ev.get("kind") == "fault.injected":
            return "fault-injection", None
    for ev in reversed(events):
        if ev.get("kind") == "sentinel.giveup":
            anomaly = str(ev.get("anomaly") or "unknown")
            sig = "numerics:%s" % (ev.get("signature") or anomaly)
            return "numerics:%s" % anomaly, sig
    err = (meta or {}).get("error")
    if err:
        sig = "%s:%s" % (err.get("type"), err.get("message"))
        return "crash:%s" % err.get("type"), sig
    return "crash:rc%d" % rc, "rc%d" % rc


#: the flake fingerprint's output bound — a real run prints epochs,
#: flight markers, result lines; a crash inside init does not (the
#: agent-side ``PodAgent._startup_shaped_log`` uses the same bound)
STARTUP_FLAKE_OUTPUT_LIMIT = 16384


def is_startup_flake(rc, out, err):
    """True when a subprocess died by an abort-class signal with a
    startup-shaped transcript — the documented sandbox XLA/glibc
    abort (ROADMAP "Known environment flake").  The crash lands inside
    backend/allocator initialization, usually before the program's
    first print but sometimes just after it (the auto-resume banner,
    glibc's own ``malloc(): invalid size`` / ``corrupted double-linked
    list`` lines), so the fingerprint is: abort-class signal, little
    output, and NO Python traceback — a Python-level death always
    leaves one; the memory-corruption class kills the process from
    under the interpreter.  A deterministic abort still fails after
    the single retry, so real native bugs cannot hide behind this.
    ``out``/``err`` must have been captured — uncaptured (None)
    streams read as "unknown output", never as a flake."""
    if out is None or err is None:
        return False
    codes = set()
    for s in STARTUP_FLAKE_SIGNALS:
        codes.add(-int(s))          # subprocess's negative-rc spelling
        codes.add(128 + int(s))     # shell-style spelling
    if rc not in codes:
        return False
    blob = out + err
    return len(blob) <= STARTUP_FLAKE_OUTPUT_LIMIT \
        and "Traceback" not in blob


def newest_mtime(paths):
    """Newest mtime across files/shallow directories, or None — THE
    progress signal: the supervisor and the pod master's agents watch
    it to tell a stuck worker from a slowly-advancing one."""
    newest = None
    for path in paths:
        try:
            if os.path.isdir(path):
                with os.scandir(path) as entries:
                    for e in entries:
                        try:
                            # no follow: quarantine leaves _current
                            # DANGLING until the next commit, and one
                            # bad symlink must not hide the rest of
                            # the directory's mtimes
                            ts = e.stat(follow_symlinks=False).st_mtime
                        except OSError:
                            continue
                        if newest is None or ts > newest:
                            newest = ts
            else:
                ts = os.path.getmtime(path)
                if newest is None or ts > newest:
                    newest = ts
        except OSError:
            continue
    return newest


def run_with_startup_retry(argv, retries=2, on_retry=None, **run_kw):
    """``subprocess.run(argv, capture_output=True, ...)`` that retries
    (twice by default — the abort rate comes in storms) when the child
    hit the sandbox XLA-startup abort (:func:`is_startup_flake`) —
    shared by the multi-process test suites and the chaos harnesses so
    each stops hand-rolling its own tolerance for the environment
    flake.  Only the flake fingerprint retries, so a deterministic
    failure costs at most ``retries`` extra runs.  Output capture is
    forced on: the flake test needs the streams."""
    run_kw.setdefault("text", True)
    run_kw["capture_output"] = True
    for attempt in range(retries + 1):
        r = subprocess.run(argv, **run_kw)
        if attempt < retries and is_startup_flake(
                r.returncode, r.stdout, r.stderr):
            flight.record("spawn.startup_flake", rc=r.returncode,
                          attempt=attempt + 1, argv=argv[:4])
            if on_retry is not None:
                on_retry(attempt + 1, r)
            continue
        return r


class Supervisor(object):
    """Spawn/respawn one training command under the policy above.

    :param argv: the full child command line (e.g.
        ``[sys.executable, "-m", "veles_tpu", "wf.py", "--snapshot",
        "auto", ...]``).
    :param progress_paths: files/directories whose newest mtime is the
        checkpoint-progress signal (typically the snapshot directory).
    :param log_dir: when set, each attempt's stdout+stderr goes to
        ``attempt-NNN.log`` inside it (the chaos harness reads these);
        default inherits the supervisor's own stdio.
    :param install_signals: forward SIGTERM/SIGINT to the child and
        stop respawning (pod preemption of the supervisor itself);
        defaults to True on the main thread, forced off elsewhere.
    """

    def __init__(self, argv, max_restarts=None, window_seconds=None,
                 backoff_base_ms=None, backoff_max_ms=None,
                 deterministic_limit=None, blackbox_dir=None,
                 progress_paths=(), log_dir=None, env=None,
                 install_signals=True, seed=None):
        def knob(value, key, default):
            if value is not None:
                return value
            return root.common.supervise.get(key, default)

        self.argv = list(argv)
        self.max_restarts = int(knob(max_restarts, "max_restarts", 8))
        self.window_seconds = float(
            knob(window_seconds, "window_seconds", 600))
        self.backoff_base = float(
            knob(backoff_base_ms, "backoff_base_ms", 200)) / 1e3
        self.backoff_max = float(
            knob(backoff_max_ms, "backoff_max_ms", 30000)) / 1e3
        self.deterministic_limit = int(
            knob(deterministic_limit, "deterministic_limit", 3))
        self.blackbox_dir = (blackbox_dir if blackbox_dir is not None
                             else root.common.blackbox.get(
                                 "dir", "artifacts"))
        self.progress_paths = list(progress_paths)
        self.log_dir = log_dir
        self.env = env
        self.install_signals = bool(install_signals)
        self._rng = random.Random(seed)
        # RLock, not Lock: the SIGTERM/SIGINT forward handler (run())
        # interrupts the main thread — possibly inside _spawn's
        # critical section — and re-enters this lock via stop() /
        # _kill_child on that same thread (VT802)
        self._lock = threading.RLock()
        self._child = None
        self._stopping = False
        self._log = logging.getLogger("Supervisor")
        #: one entry per completed attempt:
        #: {"pid", "rc", "kind", "signature", "spawned", "ended"}
        self.history = []
        self.spawn_count = 0
        self.last_spawn_ts = None
        self.restarts = {"preempt": 0, "killed": 0,
                         "fault-injection": 0, "crash": 0,
                         "numerics": 0}
        #: the reason a give-up verdict fired, or None (the chaos
        #: harnesses assert on it; mirrors the supervisor.giveup
        #: flight event)
        self.giveup_reason = None
        self.giveup_diagnosis = None

    # ----------------------------------------------------------- surface
    def current_pid(self):
        """The live child's pid, or None — the chaos harness's kill
        target."""
        with self._lock:
            if self._child is not None and self._child.poll() is None:
                return self._child.pid
        return None

    def stop(self):
        """Stop respawning and SIGTERM the live child (graceful: it
        checkpoints and exits 75; run() then returns)."""
        self._stopping = True
        with self._lock:
            child = self._child
        if child is not None and child.poll() is None:
            try:
                child.send_signal(signal.SIGTERM)
            except OSError:
                pass

    def run(self):
        """Supervise until the child finishes, the crash-loop valve
        trips, or stop()/SIGTERM; returns the final exit code."""
        prev = {}
        if self.install_signals and \
                threading.current_thread() is threading.main_thread():
            def forward(signum, frame):
                # stop respawning FIRST, then relay: the child's own
                # SIGTERM path checkpoints and exits 75
                self.stop() if signum == signal.SIGTERM \
                    else self._kill_child(signum)
            for s in (signal.SIGTERM, signal.SIGINT):
                prev[s] = signal.signal(s, forward)
        try:
            return self._loop()
        finally:
            for s, h in prev.items():
                signal.signal(s, h)

    def _kill_child(self, signum):
        self._stopping = True
        with self._lock:
            child = self._child
        if child is not None and child.poll() is None:
            try:
                child.send_signal(signum)
            except OSError:
                pass

    # -------------------------------------------------------------- loop
    def _loop(self):
        consecutive = 0          # bounded respawns since last progress
        last_signature = None
        same_signature = 0
        # the numerics valve's own counters: a replaying run COMMITS
        # (rollback/replay advances checkpoints), so unlike the crash
        # counter these never reset on checkpoint progress — identical
        # numeric divergence across lives is deterministic however
        # much the replay commits in between
        numerics_signature = None
        same_numerics = 0
        window = []              # timestamps of bounded respawns
        while True:
            marker = self._progress_marker()
            spawned = time.time()
            try:
                child, attempt_log = self._spawn()
            except OSError as e:
                # fork/exec itself can fail transiently (ENOMEM/EAGAIN
                # in the very OOM storms this loop exists to ride out)
                # — that is one more bounded, backed-off respawn, not
                # the end of supervision
                self._error("spawn failed (%s: %s)",
                            type(e).__name__, e)
                flight.record("supervisor.spawn_error", error=str(e))
                now = time.time()
                window = [t for t in window
                          if now - t < self.window_seconds]
                window.append(now)
                if len(window) > self.max_restarts or self._stopping:
                    self.giveup_reason = "spawn-error"
                    flight.record("supervisor.giveup",
                                  reason="spawn-error")
                    return 1
                consecutive += 1
                time.sleep(self.backoff_delay(consecutive))
                continue
            rc = child.wait()
            if attempt_log is not None:
                attempt_log.close()
            kind, signature = self._classify(rc, spawned)
            self.history.append({
                "pid": child.pid, "rc": rc, "kind": kind,
                "signature": signature, "spawned": spawned,
                "ended": time.time()})
            flight.record("supervisor.exit", pid=child.pid, rc=rc,
                          cause=kind)
            if kind == "done":
                self._info("child pid %d finished cleanly", child.pid)
                return 0
            if self._stopping:
                self._info("stopping — child pid %d exited %s (%s), "
                           "not respawning", child.pid, rc, kind)
                return rc
            progressed = self._progress_marker() != marker
            if progressed:
                consecutive = 0
                same_signature, last_signature = 0, None
            if kind == "preempt":
                # graceful preemption left a fresh checkpoint: the
                # normal pod lifecycle — respawn now, never bounded
                self.restarts["preempt"] += 1
                flight.record("supervisor.respawn", cause=kind,
                              delay_s=0.0)
                self._info("child pid %d preempted (exit 75) — "
                           "respawning immediately", child.pid)
                continue
            bucket = ("killed" if kind.startswith("killed")
                      else kind if kind == "fault-injection"
                      else "numerics" if kind.startswith("numerics:")
                      else "crash")
            self.restarts[bucket] += 1
            if bucket == "numerics":
                # the sentinel's rung-3 escalation (services.sentinel):
                # same deterministic-bug shape, but judged on the
                # anomaly signature ALONE — checkpoint progress from
                # the replays does not excuse identical divergence
                if signature is not None and \
                        signature == numerics_signature:
                    same_numerics += 1
                else:
                    same_numerics, numerics_signature = 1, signature
                if same_numerics >= self.deterministic_limit:
                    diagnosis = (
                        "%d consecutive identical numeric-fault "
                        "give-ups (%s) — the sentinel's rollback "
                        "ladder could not outrun the divergence; the "
                        "fault replays deterministically, restarting "
                        "will not help (checkpoints are intact; see "
                        "the sentinel.giveup crashdump for the "
                        "anomaly detail)"
                        % (same_numerics, signature))
                    self._error("giving up: %s", diagnosis)
                    self.giveup_reason = "numerics"
                    self.giveup_diagnosis = diagnosis
                    flight.record("supervisor.giveup",
                                  reason="numerics",
                                  signature=signature,
                                  diagnosis=diagnosis, rc=rc)
                    return rc or 1
            if bucket == "crash":
                if signature is not None and \
                        signature == last_signature:
                    same_signature += 1
                else:
                    same_signature, last_signature = 1, signature
                if same_signature >= self.deterministic_limit:
                    self._error(
                        "giving up: %d consecutive identical crashes "
                        "(%s) with no checkpoint progress — a "
                        "deterministic bug replays the same way from "
                        "the same checkpoint; restarting will not help",
                        same_signature, signature)
                    self.giveup_reason = "deterministic-bug"
                    self.giveup_diagnosis = signature
                    flight.record("supervisor.giveup",
                                  reason="deterministic-bug",
                                  signature=signature, rc=rc)
                    return rc or 1
            now = time.time()
            window = [t for t in window
                      if now - t < self.window_seconds]
            window.append(now)
            if len(window) > self.max_restarts:
                self._error(
                    "giving up: %d bounded respawns within %.0fs "
                    "(max %d) — crash loop", len(window),
                    self.window_seconds, self.max_restarts)
                self.giveup_reason = "crash-loop"
                flight.record("supervisor.giveup", reason="crash-loop",
                              restarts=len(window), rc=rc)
                return rc or 1
            consecutive += 1
            delay = self.backoff_delay(consecutive)
            flight.record("supervisor.respawn", cause=kind,
                          delay_s=delay)
            self._info("child pid %d died (%s, rc=%s)%s — respawn "
                       "#%d in %.2fs", child.pid, kind, rc,
                       " after checkpoint progress" if progressed
                       else "", consecutive, delay)
            deadline = time.time() + delay
            while time.time() < deadline and not self._stopping:
                time.sleep(min(0.05, max(deadline - time.time(), 0)))
            if self._stopping:
                return rc

    def backoff_delay(self, attempt):
        """Exponential backoff with jitter (module-level
        :func:`backoff_delay`, shared with the pod master) —
        test-pinned."""
        return backoff_delay(attempt, self.backoff_base,
                             self.backoff_max, self._rng)

    # ------------------------------------------------------------- spawn
    def _spawn(self):
        self.spawn_count += 1
        attempt_log = None
        stdout = stderr = None
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            attempt_log = open(
                os.path.join(self.log_dir,
                             "attempt-%03d.log" % self.spawn_count),
                "wb")
            stdout = stderr = attempt_log
        try:
            child = subprocess.Popen(self.argv, env=self.env,
                                     stdout=stdout, stderr=stderr)
        except OSError:
            if attempt_log is not None:
                attempt_log.close()
            raise
        with self._lock:
            self._child = child
        self.last_spawn_ts = time.time()
        flight.record("supervisor.spawn", pid=child.pid,
                      attempt=self.spawn_count)
        self._info("spawned pid %d (attempt %d)", child.pid,
                   self.spawn_count)
        return child, attempt_log

    # ---------------------------------------------------- classification
    def _classify(self, rc, spawned):
        """Delegates to the shared :func:`classify_exit` (the pod
        master's agents classify identically)."""
        return classify_exit(rc, self.blackbox_dir, spawned)

    # ----------------------------------------------------------- helpers
    def _progress_marker(self):
        """Newest mtime across the progress paths — checkpoint commits
        move it forward (shared scan with the pod master's agents)."""
        return newest_mtime(self.progress_paths)

    def _info(self, msg, *args):
        self._log.info(msg, *args)
        print("[supervisor] " + msg % args, file=sys.stderr, flush=True)

    def _error(self, msg, *args):
        self._log.error(msg, *args)
        print("[supervisor] " + msg % args, file=sys.stderr, flush=True)
