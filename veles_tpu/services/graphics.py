"""Out-of-process graphics over ZeroMQ pub/sub
(ref veles/graphics_server.py:73-163 + graphics_client.py:84 — the
reference broadcast snappy-pickled Plotter objects over ZMQ PUB, rendered
by a separate matplotlib process).

``GraphicsServer`` bridges the in-process :data:`plotting.bus` onto a ZMQ
PUB socket; ``GraphicsClient`` (run in any other process, or the bundled
``python -m veles_tpu.services.graphics`` entry) subscribes and renders
payloads to PNG via the same plotter renderers.  The compute loop never
blocks: publishing is fire-and-forget."""

import pickle
import threading

from veles_tpu.logger import Logger
from veles_tpu.services import plotting


class GraphicsServer(Logger):
    """PUB side.  ``endpoint="tcp://127.0.0.1:0"`` binds a random port
    (read the resolved one from ``.endpoint``).

    ``multicast="239.192.1.1"`` additionally binds an ``epgm://`` (PGM
    over UDP multicast) endpoint per non-blacklisted network interface —
    the reference's LAN plot broadcast (ref graphics_server.py:100-133;
    same default group address, config.py:211).  Clients on the same
    segment subscribe without knowing the publisher's host.  PGM support
    is optional in libzmq builds, so every epgm bind failure degrades to
    a warning; the tcp endpoint always works.  Resolved endpoints live
    in ``.endpoints`` ({"tcp": ..., "epgm": [...]})."""

    def __init__(self, endpoint="tcp://127.0.0.1:0", bus=None,
                 multicast=None, multicast_port=None, ifaces=None,
                 **kwargs):
        super(GraphicsServer, self).__init__(**kwargs)
        from veles_tpu.config import root
        self.endpoint = endpoint
        self.bus = bus if bus is not None else plotting.bus
        g = root.common.graphics
        self.multicast = (multicast if multicast is not None
                          else g.get("multicast_address", None))
        self.multicast_port = int(multicast_port if multicast_port
                                  is not None
                                  else g.get("multicast_port", 5555))
        self._ifaces = ifaces
        self._blacklist = set(g.get("blacklisted_ifaces", ()))
        self.endpoints = {"tcp": None, "epgm": []}
        self._sock = None
        self._ctx = None

    def _multicast_ifaces(self):
        if self._ifaces is not None:
            return [i for i in self._ifaces if i not in self._blacklist]
        import socket
        try:
            names = [name for _, name in socket.if_nameindex()]
        except OSError:
            return []
        return [n for n in names
                if n not in self._blacklist and n != "lo"]

    def start(self):
        import zmq
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.PUB)
        if self.endpoint.endswith(":0"):
            port = self._sock.bind_to_random_port(self.endpoint[:-2])
            self.endpoint = "%s:%d" % (self.endpoint[:-2], port)
        else:
            self._sock.bind(self.endpoint)
        self.endpoints["tcp"] = self.endpoint
        if self.multicast:
            for iface in self._multicast_ifaces():
                ep = "epgm://%s;%s:%d" % (iface, self.multicast,
                                          self.multicast_port)
                try:
                    self._sock.bind(ep)
                except zmq.ZMQError as e:
                    # libzmq without --with-pgm, or a v6/virtual iface
                    self.warning("epgm bind failed on %s: %s", ep, e)
                else:
                    self.endpoints["epgm"].append(ep)
        self.bus.subscribe(self.publish)
        self.info("graphics server on %s", "; ".join(
            [self.endpoint] + self.endpoints["epgm"]))
        return self

    def publish(self, payload):
        if self._sock is not None:
            try:
                self._sock.send(pickle.dumps(payload, protocol=4),
                                flags=1)   # NOBLOCK: never stall the loop
            except Exception:   # noqa: BLE001 — slow joiner/full HWM
                pass

    def stop(self):
        self.bus.unsubscribe(self.publish)
        if self._sock is not None:
            self._sock.close(0)
            self._sock = None


_RENDERERS = {}


def _renderer(kind):
    """kind → a plotter instance whose render() understands the payload."""
    if kind not in _RENDERERS:
        cls = {"curve": plotting.AccumulatingPlotter,
               "matrix": plotting.MatrixPlotter,
               "images": plotting.ImagePlotter,
               "histogram": plotting.HistogramPlotter,
               "multi_histogram": plotting.MultiHistogramPlotter,
               "minmax": plotting.MinMaxPlotter,
               "unit_stats": plotting.UnitStatsPlotter}.get(kind)
        _RENDERERS[kind] = cls(None) if cls is not None else None
    return _RENDERERS[kind]


class GraphicsClient(Logger):
    """SUB side: receives payloads on a background thread; ``render_all``
    writes the most recent payload per plot name to PNG files."""

    def __init__(self, endpoint, directory="plots", **kwargs):
        super(GraphicsClient, self).__init__(**kwargs)
        self.endpoint = endpoint
        self.directory = directory
        self.latest = {}      # plot name -> payload
        self.received = 0
        self._thread = None
        self._stop = False

    def start(self):
        import zmq
        ctx = zmq.Context.instance()
        sock = ctx.socket(zmq.SUB)
        sock.connect(self.endpoint)
        sock.setsockopt(zmq.SUBSCRIBE, b"")

        # the socket lives entirely on the pump thread (zmq sockets are not
        # thread-safe); stop() only flips the flag and joins
        def pump():
            poller = zmq.Poller()
            poller.register(sock, zmq.POLLIN)
            while not self._stop:
                try:
                    if not poller.poll(100):
                        continue
                    payload = pickle.loads(sock.recv(zmq.NOBLOCK))
                    if isinstance(payload, dict):
                        self.latest[payload.get("name", "plot")] = payload
                        self.received += 1
                    else:
                        self.warning("ignoring non-dict plot payload: %r",
                                     type(payload).__name__)
                except Exception:   # noqa: BLE001 — context shut down
                    break
            sock.close(0)

        self._thread = threading.Thread(target=pump, daemon=True)
        self._thread.start()
        self.info("graphics client subscribed to %s", self.endpoint)
        return self

    def render_all(self, fmt="png"):
        """Write the most recent payload per plot name; ``fmt="pdf"`` is
        the reference's SIGUSR2 PDF export (graphics_client.py)."""
        import os
        os.makedirs(self.directory, exist_ok=True)
        written = []
        for name, payload in list(self.latest.items()):
            plotter = _renderer(payload.get("kind"))
            if plotter is None:
                continue
            path = os.path.join(self.directory, "%s.%s" % (name, fmt))
            plotter.render(payload, path)
            written.append(path)
        return written

    def install_pdf_signal(self):
        """SIGUSR2 → export every current plot as PDF (ref
        graphics_client PDF export via SIGUSR2).  Main thread only."""
        import signal

        def handler(signum, frame):
            paths = self.render_all(fmt="pdf")
            self.info("SIGUSR2: exported %d pdf plot(s)", len(paths))

        signal.signal(signal.SIGUSR2, handler)

    def stop(self):
        self._stop = True
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


def main(argv=None):
    """Standalone render client: subscribe and write PNGs until killed."""
    import argparse
    import time
    p = argparse.ArgumentParser(description="veles_tpu graphics client")
    p.add_argument("endpoint")
    p.add_argument("-d", "--directory", default="plots")
    p.add_argument("--interval", type=float, default=2.0)
    args = p.parse_args(argv)
    client = GraphicsClient(args.endpoint, args.directory).start()
    client.install_pdf_signal()   # kill -USR2 <pid> → PDF export
    try:
        while True:
            time.sleep(args.interval)
            client.render_all()
    except KeyboardInterrupt:
        client.stop()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
