"""Request cost pricing for the serving fleet (the TVM/CLBlast move
from PAPERS.md applied to placement: don't guess with raw request
counts, PREDICT the cost from a calibrated model and route on it).

A serving request's device residency is priced in milliseconds as::

    cost_ms = prompt_len * prefill_ms_per_tok
              + max_new  * decode_ms_per_tok

with the per-token constants seeded from ``tools.cost_model.
serve_request_costs()`` (the same calibrated device model the MFU
check and the bench predictions use; a baked-in v5e mirror covers
installs without ``tools/``) and CALIBRATED at runtime against the
fleet's MEASURED decode ms/tok — the router feeds every replica's
``p50_ms_per_tok`` health reading through :meth:`RequestCost.
calibrate`, which rescales BOTH constants by the measured/predicted
ratio (prefill and decode share the device, so one drift factor
covers both until a measured prefill rate arrives; replicas that
report ``prefill_ms_per_tok`` pin the prefill constant directly).

Only RELATIVE accuracy matters for placement: the router ranks
replicas by their predicted outstanding work, so a fleet-wide scale
error cancels.  Absolute accuracy matters for the deadline check and
the autoscaler's backlog estimate — which is why the measured
feedback loop exists."""

import threading

#: baked v5e mirror of tools.cost_model.serve_request_costs() at the
#: flagship serving config (d=768, 12 layers) — used when tools/ is
#: not importable (installed package without the repo checkout)
_FALLBACK = {
    "prefill_ms_per_tok": 0.0012,
    "decode_ms_per_tok": 0.558,
}


def predicted_request_costs():
    """``{"prefill_ms_per_tok", "decode_ms_per_tok"}`` from the
    calibrated cost model when the repo's tools/ is importable, else
    the baked-in v5e mirror."""
    try:
        from tools.cost_model import serve_request_costs
        out = serve_request_costs()
        return {"prefill_ms_per_tok": float(out["prefill_ms_per_tok"]),
                "decode_ms_per_tok": float(out["decode_ms_per_tok"])}
    except Exception:   # noqa: BLE001 — installed without tools/
        return dict(_FALLBACK)


class RequestCost(object):
    """The fleet router's request pricer: predicted prefill work plus
    predicted decode residency, with closed-loop calibration off the
    fleet's measured rates.  Thread-safe (the health thread calibrates
    while request threads price)."""

    def __init__(self, prefill_ms_per_tok=None, decode_ms_per_tok=None):
        seed = predicted_request_costs()
        #: the model's uncalibrated decode prediction — the divisor of
        #: the measured/predicted drift factor
        self._decode_predicted = float(
            decode_ms_per_tok or seed["decode_ms_per_tok"])
        self._prefill_predicted = float(
            prefill_ms_per_tok or seed["prefill_ms_per_tok"])
        self.decode_ms_per_tok = self._decode_predicted
        self.prefill_ms_per_tok = self._prefill_predicted
        #: None until the first measured sample lands
        self.calibration = None
        self._measured_prefill = False
        self._lock = threading.Lock()

    def price(self, prompt_len, max_new):
        """Predicted device residency (ms) of one request."""
        return (max(0, int(prompt_len)) * self.prefill_ms_per_tok
                + max(0, int(max_new)) * self.decode_ms_per_tok)

    def calibrate(self, measured_decode_ms_per_tok,
                  measured_prefill_ms_per_tok=None):
        """Fold one measured sample in (EWMA so one noisy probe cannot
        swing placement): the decode constant tracks the measurement,
        and the prefill constant rescales by the same drift factor
        until a replica reports a measured prefill rate of its own."""
        m = float(measured_decode_ms_per_tok or 0.0)
        if m <= 0:
            return
        with self._lock:
            self.decode_ms_per_tok = (
                m if self.calibration is None
                else 0.8 * self.decode_ms_per_tok + 0.2 * m)
            self.calibration = (self.decode_ms_per_tok
                                / self._decode_predicted)
            mp = float(measured_prefill_ms_per_tok or 0.0)
            if mp > 0:
                self._measured_prefill = True
                self.prefill_ms_per_tok = (
                    0.8 * self.prefill_ms_per_tok + 0.2 * mp
                    if self.prefill_ms_per_tok else mp)
            elif not self._measured_prefill:
                self.prefill_ms_per_tok = (self._prefill_predicted
                                           * self.calibration)

    def status(self):
        return {"prefill_ms_per_tok": round(self.prefill_ms_per_tok, 6),
                "decode_ms_per_tok": round(self.decode_ms_per_tok, 6),
                "calibration": (round(self.calibration, 4)
                                if self.calibration is not None
                                else None)}
