"""RESTful serving (ref: veles/restful_api.py:54-217 + loader/restful.py).

``RESTfulAPI`` wraps a trained workflow's jitted forward function behind an
HTTP endpoint: POST JSON ``{"input": [...]}`` (nested lists or base64 —
the reference's two codecs, restful_api.py:112-217) returns
``{"result": [...]}``.  stdlib http.server in a daemon thread replaces the
reference's Twisted resource — no reactor to manage."""

import base64
import json
import queue as _queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from veles_tpu.logger import Logger
from veles_tpu.telemetry import flight


class GenerateBatcher(Logger):
    """Serving coalescer: concurrent generate requests arriving within
    ``window`` seconds merge into ONE device call through
    ``LMGenerator.generate_batch`` (per-row sampling params keep every
    request's random draws independent of which batch it lands in — see
    generate_batch's determinism note).  Batches pad
    up to power-of-two row counts (clamped to ``max_batch``) so the
    generator compiles O(log max_batch) executables instead of one per
    observed size.
    Modern continuous-batching-lite — the reference served strictly one
    request per forward (restful_api.py:112-217)."""

    def __init__(self, generator, window=0.01, max_batch=8):
        super(GenerateBatcher, self).__init__()
        self.generator = generator
        self.window = float(window)
        self.max_batch = int(max_batch)
        self._lock = threading.Condition()
        self._pending = []                # (prompt, opts, slot)
        self._closed = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def submit_async(self, prompt_row, opts):
        """Enqueue one row; returns a slot for ``wait``."""
        slot = {"event": threading.Event()}
        with self._lock:
            if self._closed:
                raise RuntimeError("batcher is stopped")
            self._pending.append((list(prompt_row), dict(opts), slot))
            self._lock.notify()
        return slot

    @staticmethod
    def wait(slot):
        slot["event"].wait()
        if "error" in slot:
            raise slot["error"]
        return slot["out"]

    def submit(self, prompt_row, opts):
        """Blocks until the coalesced batch ran; returns the 1-D
        output."""
        return self.wait(self.submit_async(prompt_row, opts))

    def stop(self):
        with self._lock:
            self._closed = True
            self._lock.notify()
        self._thread.join(timeout=5)

    def _loop(self):
        while True:
            with self._lock:
                while not self._pending and not self._closed:
                    self._lock.wait()
                if self._closed and not self._pending:
                    return
            time.sleep(self.window)       # collect the burst
            with self._lock:
                group = self._pending[:self.max_batch]
                del self._pending[:len(group)]
            if not group:
                continue
            prompts = [g[0] for g in group]
            opts = [g[1] for g in group]
            # pad to the next power of two with throwaway copies of row
            # 0 so compile count stays O(log max_batch); never past the
            # operator's max_batch cap (it may bound KV-cache memory)
            bucket = 1
            while bucket < len(group):
                bucket *= 2
            n_pad = min(bucket, self.max_batch) - len(group)
            # max_new=0: a pad row must never push a full-length prompt
            # past max_len and fail the group
            prompts += [prompts[0]] * n_pad
            opts += [{"max_new": 0}] * n_pad
            try:
                outs = self.generator.generate_batch(prompts, opts)
            except Exception as e:  # noqa: BLE001 — deliver per request
                for _, _, slot in group:
                    slot["error"] = e
                    slot["event"].set()
                continue
            for (_, _, slot), out in zip(group, outs):
                slot["out"] = out
                slot["event"].set()


class ContinuousEngine(Logger):
    """Background driver putting ``models.generate.ContinuousBatcher``
    behind the REST endpoint: one engine thread ticks the slot pool
    whenever work exists; each HTTP worker blocks on its request's
    event and wakes the moment its row leaves the pool.  Unlike the
    window coalescer, a request joins the CURRENT in-flight decode at
    the next tick — no batch boundary, no window wait.

    The engine thread is the ONLY caller of the (thread-unsafe)
    batcher: HTTP workers hand requests over through an ingress deque
    and read results back from their request record, so the device
    dispatch in ``tick()`` runs with NO lock held — admission latency
    stays flat no matter how long a fused dispatch takes (ADVICE r4:
    the previous design blocked every submit for a whole
    ticks_per_dispatch dispatch).

    Every request records queue-wait (submit→admitted to a slot,
    tick granularity) and decode time (admitted→finished), feeding
    ``metrics()`` — per-stream tokens/s with p50/p99, the serving
    plane's SLO surface (ref capability: per-slave stats in the web
    status table, ref web_status.py:113-200, applied to serving)."""

    def __init__(self, generator, slots=8, history=512, paged_block=0,
                 pool_tokens=None, prefix_cache=False, speculative_k=0,
                 ticks_per_dispatch=1):
        super(ContinuousEngine, self).__init__()
        import collections
        from veles_tpu.models.generate import (ContinuousBatcher,
                                               PagedContinuousBatcher)
        #: paged_block > 0: block-table KV pool — slot memory scales
        #: with the pool_tokens budget, and admission backpressures on
        #: pool exhaustion as well as slot exhaustion.  prefix_cache:
        #: concurrent requests sharing a prompt prefix share its KV
        #: blocks (copy-on-write — the system-prompt case)
        #: ticks_per_dispatch: fuse K engine ticks into one device
        #: dispatch — on a remote/tunneled device the per-dispatch
        #: round trip dominates per-token cost, so K ~ 8-32 multiplies
        #: serving throughput (admission + streaming then happen at
        #: K-token boundaries; token streams are unchanged)
        self.cb = (PagedContinuousBatcher(
                       generator, slots=slots, block=paged_block,
                       pool_tokens=pool_tokens,
                       prefix_cache=prefix_cache,
                       speculative_k=speculative_k,
                       ticks_per_dispatch=ticks_per_dispatch)
                   if paged_block else
                   ContinuousBatcher(
                       generator, slots=slots,
                       speculative_k=speculative_k,
                       ticks_per_dispatch=ticks_per_dispatch))
        #: guards _ingress / _records / _history / counters — NEVER
        #: held across a device dispatch
        self._lock = threading.Lock()
        self._ingress = collections.deque()
        self._records = {}                 # rid -> record (cb-submitted)
        self._history = collections.deque(maxlen=int(history))
        self._served = 0
        #: free-KV-block gauge, snapshotted by the ENGINE thread after
        #: each tick (metrics() must not touch the thread-unsafe
        #: batcher); None on the dense batcher
        self._kv_gauge = (self.cb.free_blocks()
                          if hasattr(self.cb, "free_blocks") else None)
        #: prefix-cache gauge: (registered shared blocks, total owner
        #: refs) — hit rate is visible as refs > blocks
        self._prefix_gauge = ((0, 0) if getattr(self.cb, "prefix_cache",
                                                False) else None)
        self._start_ts = time.monotonic()
        #: queue-wait SLO (root.common.serve.slo_queue_wait_ms): a
        #: completed request that waited longer records a flight-recorder
        #: breach event, so serving SLO violations land in the same
        #: post-mortem timeline as training stalls.  0 = no SLO.
        from veles_tpu.config import root as _root
        self._slo_queue_wait_ms = float(
            _root.common.serve.get("slo_queue_wait_ms", 0) or 0)
        self._closed = False
        self._wake = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def submit_async(self, prompt_row, max_new, temperature=0.0,
                     seed=0, adapter=0, stream=False):
        """Enqueue one row; returns a handle for ``wait`` (submit every
        row of a request BEFORE waiting so they share the pool).
        Validates here so a bad request raises in the CALLER (one 400),
        never on the engine thread.  The length checks delegate to the
        generator's canonical validate_request; only the engine-specific
        constraints (non-empty prompt, at least one new token — a slot
        must decode something to ever free itself) live here."""
        prompt = [int(t) for t in prompt_row]
        if not prompt:
            raise ValueError("empty prompt")
        if int(max_new) < 1:
            raise ValueError("max_new must be >= 1, got %d"
                             % int(max_new))
        self.cb.gen.validate_request(
            len(prompt), {"max_new": int(max_new),
                          "temperature": float(temperature)})
        spec_k = getattr(self.cb, "speculative_k", 0)
        if spec_k and len(prompt) + int(max_new) + spec_k \
                > self.cb.gen.max_len:
            raise ValueError(
                "speculative ticks draft %d positions past the "
                "cursor: prompt+max_new+k %d exceeds max_len %d"
                % (spec_k, len(prompt) + int(max_new) + spec_k,
                   self.cb.gen.max_len))
        n_bank = getattr(self.cb.gen, "_n_adapters", 0)
        if not 0 <= int(adapter) <= n_bank:
            raise ValueError("adapter %d outside the loaded bank "
                             "(0..%d)" % (int(adapter), n_bank))
        rec = {"prompt": prompt, "max_new": int(max_new),
               "temperature": float(temperature), "seed": int(seed),
               "adapter": int(adapter),
               "event": threading.Event(), "submit_ts": time.monotonic(),
               "admit_ts": None, "out": None, "error": None,
               # streaming: the engine thread pushes ("tokens", [...])
               # chunks of NEW tokens per dispatch, then ("done", out)
               # / ("error", e); the HTTP worker drains until a
               # terminal item.  _sent tracks the high-water mark.
               "stream_q": _queue.Queue() if stream else None,
               "_sent": 0}
        with self._lock:
            if self._closed:
                raise RuntimeError("engine is stopped")
            self._ingress.append(rec)
        self._wake.set()
        flight.record("serve.submit", prompt_len=len(prompt),
                      max_new=int(max_new), stream=bool(stream))
        return rec

    @staticmethod
    def wait(handle):
        handle["event"].wait()
        if handle["error"] is not None:
            raise handle["error"]
        return np.asarray(handle["out"], np.int32)

    def submit(self, prompt_row, max_new, temperature=0.0, seed=0,
               adapter=0):
        """Block until this request's row finishes; returns the 1-D
        prompt+continuation array."""
        return self.wait(self.submit_async(prompt_row, max_new,
                                           temperature=temperature,
                                           seed=seed, adapter=adapter))

    def stream(self, prompt_row, max_new, temperature=0.0, seed=0,
               adapter=0):
        """Generator yielding lists of NEW tokens as they decode
        (one chunk per engine dispatch — ``ticks_per_dispatch`` tokens
        at a time), ending after the final chunk.  Raises the engine's
        error if the request fails."""
        rec = self.submit_async(prompt_row, max_new,
                                temperature=temperature, seed=seed,
                                adapter=adapter, stream=True)
        while True:
            kind, payload = rec["stream_q"].get()
            if kind == "tokens":
                yield payload
            elif kind == "done":
                return
            else:
                raise payload

    def _loop(self):
        while True:
            with self._lock:
                if self._closed:
                    return
                new = list(self._ingress)
                self._ingress.clear()
            for rec in new:           # engine thread: sole cb caller
                try:
                    rid = self.cb.submit(rec["prompt"], rec["max_new"],
                                         adapter=rec.get("adapter", 0),
                                         temperature=rec["temperature"],
                                         seed=rec["seed"])
                except Exception as e:  # noqa: BLE001 — deliver to waiter
                    rec["error"] = e
                    if rec["stream_q"] is not None:
                        rec["stream_q"].put(("error", e))
                    rec["event"].set()
                    continue
                with self._lock:
                    if self._closed:   # stop() raced the hand-off —
                        rec["error"] = RuntimeError(  # release the waiter
                            "engine stopped before request completed")
                        if rec["stream_q"] is not None:
                            rec["stream_q"].put(("error", rec["error"]))
                        rec["event"].set()
                        continue
                    self._records[rid] = rec
            if self.cb.idle():
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            with self._lock:
                self.cb.stream_partials = any(
                    rec["stream_q"] is not None
                    for rec in self._records.values())
            tick_start = time.monotonic()
            self.cb.tick()            # device dispatch — NO lock held
            now = time.monotonic()
            active = self.cb.active_requests()
            done = []
            with self._lock:
                for rid, rec in self._records.items():
                    admitted = rid in active or \
                        self.cb.result(rid) is not None
                    if rec["admit_ts"] is None and admitted:
                        # admission happened in THIS tick's admit phase
                        # — stamp its start, so a request that also
                        # finishes within the tick (short max_new,
                        # fused dispatch) records the tick's real
                        # duration as decode time, not a 1e-9 floor
                        rec["admit_ts"] = tick_start
                        # flight gets the REAL admission (serve.submit
                        # marked the enqueue): the gap between the two
                        # is the queue wait a post-mortem measures
                        flight.record(
                            "serve.admit",
                            prompt_len=len(rec["prompt"]),
                            queue_wait_ms=(tick_start
                                           - rec["submit_ts"]) * 1e3)
                for rid, rec in self._records.items():
                    if rec["stream_q"] is None:
                        continue
                    part = self.cb.partial(rid)
                    if part is None:
                        continue
                    fresh = part[len(rec["prompt"]) + rec["_sent"]:]
                    if fresh:
                        rec["_sent"] += len(fresh)
                        rec["stream_q"].put(("tokens", fresh))
                for rid in list(self._records):
                    out = self.cb.pop_result(rid)
                    if out is None:
                        continue
                    rec = self._records.pop(rid)
                    rec["out"] = out
                    done.append(rec)
                    dec = max(1e-9, now - (rec["admit_ts"] or now))
                    n_new = len(out) - len(rec["prompt"])
                    qw_ms = ((rec["admit_ts"] or now)
                             - rec["submit_ts"]) * 1e3
                    rec["_queue_wait_ms"] = qw_ms
                    self._history.append({
                        "queue_wait_ms": qw_ms,
                        "decode_ms": dec * 1e3,
                        "new_tokens": n_new,
                        "tokens_per_sec": n_new / dec,
                        "ms_per_tok": dec * 1e3 / max(1, n_new),
                        "finish_ts": now})
                    self._served += 1
            if self._kv_gauge is not None:
                with self._lock:
                    self._kv_gauge = self.cb.free_blocks()
                    if self._prefix_gauge is not None:
                        self._prefix_gauge = self.cb.prefix_stats()
            for rec in done:          # wake waiters outside the lock
                if self._slo_queue_wait_ms and \
                        rec.get("_queue_wait_ms", 0.0) \
                        > self._slo_queue_wait_ms:
                    flight.record(
                        "serve.slo_breach",
                        queue_wait_ms=rec["_queue_wait_ms"],
                        slo_ms=self._slo_queue_wait_ms,
                        prompt_len=len(rec["prompt"]))
                if rec["stream_q"] is not None:
                    # the batcher drops its partial snapshot when the
                    # row completes — flush whatever the last dispatch
                    # decoded from the final result before the terminal
                    tail = list(rec["out"])[len(rec["prompt"])
                                            + rec["_sent"]:]
                    if tail:
                        rec["_sent"] += len(tail)
                        rec["stream_q"].put(("tokens", tail))
                    rec["stream_q"].put(("done", rec["out"]))
                rec["event"].set()

    def metrics(self):
        """Serving-plane SLO snapshot: queue depth, in-flight rows,
        served count, p50/p99 queue-wait and per-stream decode rate
        over the last ``history`` completed requests."""
        with self._lock:
            hist = list(self._history)
            queued = len(self._ingress) + sum(
                1 for r in self._records.values()
                if r["admit_ts"] is None)
            in_flight = sum(1 for r in self._records.values()
                            if r["admit_ts"] is not None)
            served = self._served
        out = {"served": served, "queued": queued,
               "in_flight": in_flight, "slots": self.cb.slots,
               "uptime_s": round(time.monotonic() - self._start_ts, 1),
               "agg_tokens_per_sec": 0.0}
        if self._kv_gauge is not None:
            out["free_kv_blocks"] = self._kv_gauge
        if self._prefix_gauge is not None:
            out["prefix_shared_blocks"] = self._prefix_gauge[0]
            out["prefix_block_refs"] = self._prefix_gauge[1]

        def pct(vals, q):
            if not vals:
                return 0.0
            vals = sorted(vals)
            return round(vals[min(len(vals) - 1,
                                  int(q / 100.0 * len(vals)))], 3)

        for key in ("queue_wait_ms", "ms_per_tok", "tokens_per_sec"):
            vals = [h[key] for h in hist]
            out["p50_" + key] = pct(vals, 50)
            out["p99_" + key] = pct(vals, 99)
        if len(hist) >= 2:
            # pool-level throughput: all new tokens in the history
            # window over the window's wall span (concurrent streams
            # overlap — summing per-stream decode times would undercount)
            span = hist[-1]["finish_ts"] - hist[0]["finish_ts"]
            if span > 1e-9:
                out["agg_tokens_per_sec"] = round(
                    sum(h["new_tokens"] for h in hist[1:]) / span, 1)
        return out

    def reset_metrics(self):
        """Clear the latency history and served counter (e.g. after a
        warmup request whose first-dispatch compile time would pollute
        the percentiles)."""
        with self._lock:
            self._history.clear()
            self._served = 0
            self._start_ts = time.monotonic()

    def stop(self):
        with self._lock:
            self._closed = True
            # release every waiter: queued records error out, in-flight
            # ones too (wait() raises instead of hanging forever)
            pending = list(self._ingress) + list(self._records.values())
            self._ingress.clear()
            self._records.clear()
        for rec in pending:
            if rec["out"] is None and rec["error"] is None:
                rec["error"] = RuntimeError(
                    "engine stopped before request completed")
            if rec.get("stream_q") is not None and rec["out"] is None:
                # a streaming consumer blocks in stream_q.get(), not on
                # the event — it needs its own terminal or it hangs
                rec["stream_q"].put(("error", rec["error"]))
            rec["event"].set()
        self._wake.set()
        self._thread.join(timeout=5)


class RESTfulAPI(Logger):
    def __init__(self, forward, input_shape, host="127.0.0.1", port=8180,
                 path="/service", generator=None, batch_window=0.0,
                 max_batch=8, continuous_slots=0, paged_block=0,
                 pool_tokens=None, prefix_cache=False,
                 speculative_k=0, ticks_per_dispatch=1):
        super(RESTfulAPI, self).__init__()
        self.forward = forward            # callable(np.ndarray) -> ndarray
        self.input_shape = tuple(input_shape)
        self.host, self.port, self.path = host, port, path
        #: models.generate.LMGenerator — enables the ``"generate"``
        #: request form for causal-LM workflows
        self.generator = generator
        #: batch_window > 0: coalesce concurrent generate requests into
        #: one device call (GenerateBatcher)
        self.batcher = (GenerateBatcher(generator, batch_window,
                                        max_batch)
                        if generator is not None and batch_window > 0
                        else None)
        #: continuous_slots > 0: in-flight batching — requests join the
        #: live decode at the next tick (ContinuousEngine; greedy and
        #: plain-temperature requests only, top_k/top_p/beam/speculative
        #: fall through to the other paths)
        self.engine = (ContinuousEngine(generator, continuous_slots,
                                        paged_block=paged_block,
                                        pool_tokens=pool_tokens,
                                        prefix_cache=prefix_cache,
                                        speculative_k=speculative_k,
                                        ticks_per_dispatch=
                                        ticks_per_dispatch)
                       if generator is not None and continuous_slots > 0
                       else None)
        self._server = None
        self._thread = None

    # ------------------------------------------------------------- server
    def start(self):
        api = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path != api.path + "/metrics":
                    self.send_error(404)
                    return
                body = json.dumps(api.serving_metrics()).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                if self.path != api.path:
                    self.send_error(404)
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length))
                    if isinstance(req.get("generate"), dict) and \
                            req["generate"].get("stream"):
                        # NDJSON streaming: one {"tokens": [...]} line
                        # per engine dispatch, then {"done", "result"}.
                        # HTTP/1.0 semantics — body is EOF-delimited,
                        # so no Content-Length / chunking needed.
                        prompt, chunks = api.run_generate_stream(req)
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/x-ndjson")
                        self.end_headers()
                        got = list(prompt)
                        # headers are out: a mid-stream failure must
                        # surface as a structured NDJSON error line,
                        # never as a 400 status injected into the body
                        try:
                            for fresh in chunks:
                                got.extend(fresh)
                                self.wfile.write(
                                    (json.dumps({"tokens": fresh})
                                     + "\n").encode())
                                self.wfile.flush()
                            self.wfile.write(
                                (json.dumps({"done": True,
                                             "result": got})
                                 + "\n").encode())
                        except Exception as e:  # noqa: BLE001
                            self.wfile.write(
                                (json.dumps({"error": str(e)})
                                 + "\n").encode())
                        return
                    if "generate" in req:
                        out = api.run_generate(req)
                    else:
                        out = np.asarray(api.forward(api.decode_input(req)))
                    body = json.dumps({"result": out.tolist()}).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except Exception as e:  # noqa: BLE001 — report to client
                    msg = json.dumps({"error": str(e)}).encode()
                    self.send_response(400)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(msg)))
                    self.end_headers()
                    self.wfile.write(msg)

            def log_message(self, fmt, *args):
                api.debug("http: " + fmt, *args)

        class Server(ThreadingHTTPServer):
            # socketserver's default listen backlog of 5 resets
            # connections under a concurrent client burst
            request_queue_size = 128

        self._server = Server((self.host, self.port), Handler)
        self.port = self._server.server_address[1]   # resolve port 0
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        self.info("serving on http://%s:%d%s", self.host, self.port,
                  self.path)

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server = None
        if self.batcher is not None:
            self.batcher.stop()
        if self.engine is not None:
            self.engine.stop()

    def serving_metrics(self):
        """GET ``{path}/metrics``: the serving plane's SLO surface —
        ContinuousEngine latency percentiles when the slot pool is on,
        plus which serving paths are active."""
        out = {"paths": {
            "continuous": self.engine is not None,
            "coalescing": self.batcher is not None,
            "generate": self.generator is not None}}
        if self.engine is not None:
            out["continuous"] = self.engine.metrics()
        return out

    # ---------------------------------------------------------- generation
    @staticmethod
    def _plain_engine_request(opts):
        """True iff this generate request can ride the slot pool:
        plain greedy/temperature, at least one new token — the ONE
        predicate the engine branch, the adapter gate, and the
        streaming gate all share (three hand-copies drifted once
        already)."""
        return (int(opts.get("beam", 0)) <= 1
                and not int(opts.get("speculative", 0))
                and int(opts.get("top_k", 0)) == 0
                and float(opts.get("top_p", 1.0)) >= 1.0
                and int(opts.get("max_new", 16)) >= 1)

    def run_generate_stream(self, req):
        """NDJSON token streaming: validates a single-row greedy /
        plain-temperature engine request and returns (prompt, iterator
        over new-token chunks).  Everything else must use the buffered
        endpoint — streaming has no batch to coalesce and no beam
        state to surface incrementally."""
        if self.generator is None:
            raise ValueError("this endpoint serves a non-LM workflow: "
                             "no generator is attached")
        opts = req.get("generate")
        if not isinstance(opts, dict):
            raise ValueError("'generate' must be an options object")
        if self.engine is None:
            raise ValueError("\"stream\" requires the continuous "
                             "engine (continuous_slots>0)")
        prompt = np.asarray(req["input"], np.int32)
        if prompt.ndim == 1:
            prompt = prompt[None]
        if prompt.shape[0] != 1:
            raise ValueError("\"stream\" serves ONE row per request")
        if not self._plain_engine_request(opts):
            raise ValueError("\"stream\" supports plain greedy/"
                             "temperature requests only")
        self.generator.validate_request(len(prompt[0]), opts)
        it = self.engine.stream(
            prompt[0], int(opts.get("max_new", 16)),
            temperature=float(opts.get("temperature", 0.0)),
            seed=int(opts.get("seed", 0)),
            adapter=int(opts.get("adapter", 0)))
        return prompt[0].tolist(), it

    def run_generate(self, req):
        """``{"input": [[tok, ...]], "generate": {"max_new": N,
        "temperature": T, "seed": S}}`` → generated token matrix (causal
        LM serving; needs ``generator=``)."""
        if self.generator is None:
            raise ValueError("this endpoint serves a non-LM workflow: "
                             "no generator is attached")
        opts = req.get("generate")
        if not isinstance(opts, dict):
            # null/false/0/[] must not silently mean "generate with
            # defaults" — only an options object selects this endpoint
            raise ValueError(
                "'generate' must be an options object like "
                "{\"max_new\": 16}, got %r" % (opts,))
        prompt = np.asarray(req["input"], np.int32)
        if prompt.ndim == 1:
            prompt = prompt[None]
        if int(opts.get("adapter", 0)) and (
                self.engine is None
                or not self._plain_engine_request(opts)):
            # adapter routing lives in the slot pool's tick; every
            # other path runs un-adapted params and would silently
            # serve the base model
            raise ValueError("\"adapter\" routing requires the "
                             "continuous engine (continuous_slots>0) "
                             "and a plain greedy/temperature request")
        beam = int(opts.get("beam", 0))
        if beam > 1:
            out, _ = self.generator.beam_search(
                prompt, int(opts.get("max_new", 16)), beam=beam)
            return out
        spec = int(opts.get("speculative", 0))
        if (spec and prompt.shape[0] == 1 and self.batcher is None
                and float(opts.get("temperature", 0.0)) == 0.0):
            # greedy single-row requests can opt into in-jit n-gram
            # speculation (exact greedy semantics; generate_speculative
            # falls back itself when speculation can't apply)
            return self.generator.generate_speculative(
                prompt, int(opts.get("max_new", 16)), draft_k=spec)
        if self.engine is not None and int(opts.get("top_k", 0)) == 0 \
                and float(opts.get("top_p", 1.0)) >= 1.0 \
                and int(opts.get("max_new", 16)) >= 1:
            # (beam/speculative were dispatched above; a speculative
            # request that fell through — batcher attached, sampled,
            # or multi-row — rides the pool as plain decode, as
            # before.  max_new=0 echo/score requests fall through —
            # the solo and coalescing paths serve them; the slot pool
            # can't)
            for row in prompt:
                self.generator.validate_request(len(row), opts)
            handles = [self.engine.submit_async(
                row, int(opts.get("max_new", 16)),
                temperature=float(opts.get("temperature", 0.0)),
                seed=int(opts.get("seed", 0)),
                adapter=int(opts.get("adapter", 0))) for row in prompt]
            return np.stack([self.engine.wait(h) for h in handles])
        if self.batcher is not None:
            # validate THIS request up front — a bad one must 400 alone,
            # never poison the batch it would have coalesced into
            for row in prompt:
                self.generator.validate_request(len(row), opts)
            # coalesce with whatever else is in flight; a request's
            # rows share its opts, outputs re-stack to the input shape
            # (enqueue every row BEFORE waiting so one request's rows
            # ride a single batch)
            slots = [self.batcher.submit_async(row, opts)
                     for row in prompt]
            return np.stack([self.batcher.wait(s) for s in slots])
        return self.generator.generate(
            prompt, int(opts.get("max_new", 16)),
            temperature=float(opts.get("temperature", 0.0)),
            seed=int(opts.get("seed", 0)),
            top_k=int(opts.get("top_k", 0)),
            top_p=float(opts.get("top_p", 1.0)))

    # ------------------------------------------------------------ decoding
    def decode_input(self, req):
        """codec 'list' (default): nested lists; codec 'base64': raw
        float32 little-endian bytes with explicit shape (ref restful
        input contract)."""
        codec = req.get("codec", "list")
        if codec == "base64":
            raw = base64.b64decode(req["input"])
            shape = tuple(req.get("shape") or (-1,) + self.input_shape)
            x = np.frombuffer(raw, dtype=np.float32).reshape(shape)
        elif codec == "list":
            x = np.asarray(req["input"], np.float32)
        else:
            raise ValueError("unknown codec %r" % codec)
        if x.ndim == len(self.input_shape):   # single sample
            x = x[None]
        expect = x.shape[1:]
        if tuple(expect) != self.input_shape and \
                int(np.prod(expect)) != int(np.prod(self.input_shape)):
            raise ValueError("input shape %s incompatible with %s"
                             % (expect, self.input_shape))
        return x.reshape((len(x),) + self.input_shape)
