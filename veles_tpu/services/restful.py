"""RESTful serving (ref: veles/restful_api.py:54-217 + loader/restful.py).

``RESTfulAPI`` wraps a trained workflow's jitted forward function behind an
HTTP endpoint: POST JSON ``{"input": [...]}`` (nested lists or base64 —
the reference's two codecs, restful_api.py:112-217) returns
``{"result": [...]}``.  stdlib http.server in a daemon thread replaces the
reference's Twisted resource — no reactor to manage."""

import base64
import json
import math
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from veles_tpu.logger import Logger
from veles_tpu.services.lifecycle import (BoundedStream, DeadlineExceeded,
                                          DrainState, EngineUnavailable,
                                          RequestCancelled, ShedError,
                                          SloShedder)
from veles_tpu.telemetry import flight, tracing


def send_json(handler, code, payload, headers=()):
    """Shared JSON-response helper for the stdlib serving handlers
    (this endpoint's and the fleet router's) — ONE place for the
    Content-Type / Content-Length / extra-headers dance so the two
    surfaces cannot drift."""
    msg = json.dumps(payload, default=str).encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(msg)))
    for k, v in headers:
        handler.send_header(k, v)
    handler.end_headers()
    handler.wfile.write(msg)


class GenerateBatcher(Logger):
    """Serving coalescer: concurrent generate requests arriving within
    ``window`` seconds merge into ONE device call through
    ``LMGenerator.generate_batch`` (per-row sampling params keep every
    request's random draws independent of which batch it lands in — see
    generate_batch's determinism note).  Batches pad
    up to power-of-two row counts (clamped to ``max_batch``) so the
    generator compiles O(log max_batch) executables instead of one per
    observed size.
    Modern continuous-batching-lite — the reference served strictly one
    request per forward (restful_api.py:112-217)."""

    def __init__(self, generator, window=0.01, max_batch=8):
        super(GenerateBatcher, self).__init__()
        self.generator = generator
        self.window = float(window)
        self.max_batch = int(max_batch)
        self._lock = threading.Condition()
        self._pending = []                # (prompt, opts, slot)
        self._closed = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def submit_async(self, prompt_row, opts):
        """Enqueue one row; returns a slot for ``wait``."""
        slot = {"event": threading.Event()}
        with self._lock:
            if self._closed:
                raise EngineUnavailable("batcher is stopped")
            self._pending.append((list(prompt_row), dict(opts), slot))
            self._lock.notify()
        return slot

    @staticmethod
    def wait(slot):
        slot["event"].wait()
        if "error" in slot:
            raise slot["error"]
        return slot["out"]

    def submit(self, prompt_row, opts):
        """Blocks until the coalesced batch ran; returns the 1-D
        output."""
        return self.wait(self.submit_async(prompt_row, opts))

    def pending(self):
        """Requests waiting for a coalesced batch (the drain watcher's
        in-flight signal for this path)."""
        with self._lock:
            return len(self._pending)

    def stop(self):
        with self._lock:
            self._closed = True
            self._lock.notify()
        self._thread.join(timeout=5)

    def _loop(self):
        while True:
            with self._lock:
                while not self._pending and not self._closed:
                    self._lock.wait()
                if self._closed and not self._pending:
                    return
            time.sleep(self.window)       # collect the burst
            with self._lock:
                group = self._pending[:self.max_batch]
                del self._pending[:len(group)]
            if not group:
                continue
            prompts = [g[0] for g in group]
            opts = [g[1] for g in group]
            # pad to the next power of two with throwaway copies of row
            # 0 so compile count stays O(log max_batch); never past the
            # operator's max_batch cap (it may bound KV-cache memory)
            bucket = 1
            while bucket < len(group):
                bucket *= 2
            n_pad = min(bucket, self.max_batch) - len(group)
            # max_new=0: a pad row must never push a full-length prompt
            # past max_len and fail the group
            prompts += [prompts[0]] * n_pad
            opts += [{"max_new": 0}] * n_pad
            try:
                outs = self.generator.generate_batch(prompts, opts)
            except Exception as e:  # noqa: BLE001 — deliver per request
                for _, _, slot in group:
                    slot["error"] = e
                    slot["event"].set()
                continue
            for (_, _, slot), out in zip(group, outs):
                slot["out"] = out
                slot["event"].set()


class ContinuousEngine(Logger):
    """Background driver putting ``models.generate.ContinuousBatcher``
    behind the REST endpoint: one engine thread ticks the slot pool
    whenever work exists; each HTTP worker blocks on its request's
    event and wakes the moment its row leaves the pool.  Unlike the
    window coalescer, a request joins the CURRENT in-flight decode at
    the next tick — no batch boundary, no window wait.

    The engine thread is the ONLY caller of the (thread-unsafe)
    batcher: HTTP workers hand requests over through an ingress deque
    and read results back from their request record, so the device
    dispatch in ``tick()`` runs with NO lock held — admission latency
    stays flat no matter how long a fused dispatch takes (ADVICE r4:
    the previous design blocked every submit for a whole
    ticks_per_dispatch dispatch).

    Every request records queue-wait (submit→admitted to a slot,
    tick granularity) and decode time (admitted→finished), feeding
    ``metrics()`` — per-stream tokens/s with p50/p99, the serving
    plane's SLO surface (ref capability: per-slave stats in the web
    status table, ref web_status.py:113-200, applied to serving)."""

    def __init__(self, generator, slots=8, history=512, paged_block=0,
                 pool_tokens=None, prefix_cache=False, speculative_k=0,
                 ticks_per_dispatch=1, prefill_segment=None,
                 prefill_tick_budget=None):
        super(ContinuousEngine, self).__init__()
        import collections
        from veles_tpu.models.generate import (ContinuousBatcher,
                                               PagedContinuousBatcher,
                                               parse_paged_block)
        from veles_tpu.config import root as _root
        serve_cfg = _root.common.serve
        #: segmented prefill admission (docs/services.md
        #: "Disaggregated prefill"): root.common.serve.prefill_segment
        #: > 0 bounds how many prompt tokens one admission may prefill
        #: per device pass — long prompts stage and interleave with
        #: decode ticks, so in-flight streams keep their cadence.
        #: None = the config knob; explicit 0 turns it off.
        if prefill_segment is None:
            prefill_segment = int(serve_cfg.get("prefill_segment", 0)
                                  or 0)
        if prefill_tick_budget is None:
            prefill_tick_budget = int(
                serve_cfg.get("prefill_tick_budget", 0) or 0)
        #: paged_block > 0: block-table KV pool — slot memory scales
        #: with the pool_tokens budget, and admission backpressures on
        #: pool exhaustion as well as slot exhaustion; "auto"/-1 keeps
        #: paged KV but lets the pool block resolve through config >
        #: the kernel autotuner > default (docs/perf.md "Autotuning").
        #: prefix_cache: concurrent requests sharing a prompt prefix
        #: share its KV blocks (copy-on-write — the system-prompt case)
        #: ticks_per_dispatch: fuse K engine ticks into one device
        #: dispatch — on a remote/tunneled device the per-dispatch
        #: round trip dominates per-token cost, so K ~ 8-32 multiplies
        #: serving throughput (admission + streaming then happen at
        #: K-token boundaries; token streams are unchanged)
        paged, block = parse_paged_block(paged_block)
        self.cb = (PagedContinuousBatcher(
                       generator, slots=slots, block=block,
                       pool_tokens=pool_tokens,
                       prefix_cache=prefix_cache,
                       speculative_k=speculative_k,
                       ticks_per_dispatch=ticks_per_dispatch,
                       prefill_segment=prefill_segment,
                       prefill_tick_budget=prefill_tick_budget)
                   if paged else
                   ContinuousBatcher(
                       generator, slots=slots,
                       speculative_k=speculative_k,
                       ticks_per_dispatch=ticks_per_dispatch,
                       prefill_segment=prefill_segment,
                       prefill_tick_budget=prefill_tick_budget))
        #: the batcher reports every staged prefill pass here (engine
        #: thread — the sole tick caller): serve.prefill flight events,
        #: the measured prefill rate the predictive deadline check
        #: uses, and the prefill gauges all feed off it
        self.cb.prefill_observer = self._note_prefill
        #: guards _ingress / _records / _history / counters — NEVER
        #: held across a device dispatch
        self._lock = threading.Lock()
        self._ingress = collections.deque()
        self._records = {}                 # rid -> record (cb-submitted)
        self._history = collections.deque(maxlen=int(history))
        self._served = 0
        #: free-KV-block gauge, snapshotted by the ENGINE thread after
        #: each tick (metrics() must not touch the thread-unsafe
        #: batcher); None on the dense batcher
        self._kv_gauge = (self.cb.free_blocks()
                          if hasattr(self.cb, "free_blocks") else None)
        #: prefix-cache gauge: (registered shared blocks, total owner
        #: refs) — hit rate is visible as refs > blocks
        self._prefix_gauge = ((0, 0) if getattr(self.cb, "prefix_cache",
                                                False) else None)
        self._start_ts = time.monotonic()
        #: queue-wait SLO (root.common.serve.slo_queue_wait_ms): a
        #: completed request that waited longer records a flight-recorder
        #: breach event, so serving SLO violations land in the same
        #: post-mortem timeline as training stalls — AND the same
        #: threshold drives the closed-loop admission shedder
        #: (services.lifecycle.SloShedder): past it, new work is
        #: rejected with ShedError (503 + Retry-After) instead of
        #: queued into a breach.  0 = no SLO, no shedding.
        self._slo_queue_wait_ms = float(
            serve_cfg.get("slo_queue_wait_ms", 0) or 0)
        self._shed = SloShedder(
            self._slo_queue_wait_ms,
            close_fraction=float(
                serve_cfg.get("shed_close_fraction", 0.5)))
        #: request lifecycle (services.lifecycle): every request gets
        #: an id, an optional deadline, and a cancel path
        self._default_deadline_ms = float(
            serve_cfg.get("default_deadline_ms", 0) or 0)
        self._stream_capacity = int(
            serve_cfg.get("stream_queue_chunks", 64))
        self._stream_overflow = str(
            serve_cfg.get("stream_overflow", "drop_oldest"))
        self._stream_stall_s = float(
            serve_cfg.get("stream_stall_timeout_ms", 10000)) / 1e3
        self._next_req_id = 0
        self._by_id = {}                   # req id -> rec (any state)
        self._cancels = collections.deque()  # req ids to cancel
        self._cancelled = 0
        self._deadline_expired = 0
        self._engine_faults = 0
        self._stream_dropped = 0
        self._spec_mixed = False
        #: segmented-prefill surface: total prefill tokens/segments
        #: the engine has advanced, the measured prefill rate (EWMA
        #: over staged chunk passes — feeds the predictive deadline
        #: check), and the prefill backlog gauge (snapshotted by the
        #: engine thread after each tick, like _kv_gauge)
        self._prefill_tokens = 0
        self._prefill_segments = 0
        self._prefill_ms_per_tok = 0.0
        self._prefill_backlog = 0
        #: decode-tick stall: wall gap between the END of one decode
        #: dispatch and the START of the next while rows were decoding
        #: — the time admissions/prefill stole from in-flight streams.
        #: THE number segmented prefill exists to bound.
        self._stall_hist = collections.deque(maxlen=int(history))
        self._last_tick_end = None
        self._had_active = False
        self._gauges = None
        #: request tracing (telemetry.tracing, docs/services.md
        #: "Request tracing"): apply the trace knobs to the process
        #: span store here — the engine starts after CLI config files
        #: ran, the store's module singleton may not have
        trace_cfg = _root.common.trace
        tracing.store.enabled = bool(trace_cfg.get("enabled", True))
        tracing.store.set_capacity(
            trace_cfg.get("capacity", tracing.DEFAULT_CAPACITY),
            trace_cfg.get("max_spans", tracing.DEFAULT_MAX_SPANS))
        #: per-phase completed-request histogram (lazy, fail-soft)
        self._phase_hist = None
        self._closed = False
        self._wake = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def submit_async(self, prompt_row, max_new, temperature=0.0,
                     seed=0, adapter=0, stream=False, deadline_ms=None,
                     shed_exempt=False, trace=None, parent_span=None):
        """Enqueue one row; returns a handle for ``wait`` (submit every
        row of a request BEFORE waiting so they share the pool).
        Validates here so a bad request raises in the CALLER (one 400),
        never on the engine thread.  The length checks delegate to the
        generator's canonical validate_request; only the engine-specific
        constraints (non-empty prompt, at least one new token — a slot
        must decode something to ever free itself) live here.

        ``deadline_ms``: wall budget from NOW for the whole request
        (None/0 falls back to root.common.serve.default_deadline_ms;
        0 there too = no deadline).  An expired request is cancelled —
        before admission if possible, mid-decode otherwise — and its
        waiter raises DeadlineExceeded.  Raises ShedError (the REST
        layer's 503 + Retry-After) while the SLO shedder is open —
        unless ``shed_exempt``: a fleet router's failover resume is
        already-admitted work being RELOCATED off a dead replica, and
        shedding it would turn one replica's death into lost requests
        (plus waste every token the fleet already decoded for them).

        ``trace``/``parent_span``: the request's trace context
        (telemetry.tracing) — every flight event and span this request
        produces keys on it, so one cross-process timeline
        reconstructs end to end."""
        if not shed_exempt and self._shed.should_shed():
            ra = self._shed.shed()
            flight.record("serve.shed", prompt_len=len(prompt_row),
                          max_new=int(max_new),
                          retry_after_s=ra, trace=trace)
            raise ShedError(
                "admission shedding: measured queue wait exceeds the "
                "%.0f ms SLO (root.common.serve.slo_queue_wait_ms) — "
                "retry after %.0f s" % (self._slo_queue_wait_ms, ra),
                retry_after_s=ra)
        prompt = [int(t) for t in prompt_row]
        if not prompt:
            raise ValueError("empty prompt")
        if int(max_new) < 1:
            raise ValueError("max_new must be >= 1, got %d"
                             % int(max_new))
        self.cb.gen.validate_request(
            len(prompt), {"max_new": int(max_new),
                          "temperature": float(temperature)})
        spec_k = getattr(self.cb, "speculative_k", 0)
        if spec_k and len(prompt) + int(max_new) + spec_k \
                > self.cb.gen.max_len:
            raise ValueError(
                "speculative ticks draft %d positions past the "
                "cursor: prompt+max_new+k %d exceeds max_len %d"
                % (spec_k, len(prompt) + int(max_new) + spec_k,
                   self.cb.gen.max_len))
        n_bank = getattr(self.cb.gen, "_n_adapters", 0)
        if not 0 <= int(adapter) <= n_bank:
            raise ValueError("adapter %d outside the loaded bank "
                             "(0..%d)" % (int(adapter), n_bank))
        if getattr(self.cb, "speculative_k", 0) \
                and float(temperature) != 0.0:
            # speculation routes PER ROW (_make_core_spec): a sampled
            # request advances one token per tick itself, but the
            # greedy rows around it keep their full speculation —
            # byte-identical to an all-greedy pool (test-pinned).  The
            # old pool-wide `serve.spec_degraded` cliff event is
            # retired; this informational one-shot only notes that the
            # pool is mixed (the sampled ROW pays the K-wide verify
            # for single-token progress).  Check-and-set under the
            # lock so concurrent HTTP workers cannot double-emit it.
            with self._lock:
                first = not self._spec_mixed
                self._spec_mixed = True
            if first:
                flight.record("serve.spec_mixed",
                              speculative_k=int(self.cb.speculative_k))
        now = time.monotonic()
        eff_deadline_ms = (float(deadline_ms) if deadline_ms
                           else self._default_deadline_ms)
        if eff_deadline_ms < 0:
            raise ValueError("deadline_ms must be >= 0, got %r"
                             % (deadline_ms,))
        rec = {"prompt": prompt, "max_new": int(max_new),
               "temperature": float(temperature), "seed": int(seed),
               "adapter": int(adapter),
               "event": threading.Event(), "submit_ts": now,
               "admit_ts": None, "out": None, "error": None,
               #: absolute monotonic deadline (None = unbounded)
               "deadline": (now + eff_deadline_ms / 1e3
                            if eff_deadline_ms else None),
               #: batcher request id once cb-submitted (cancel needs it)
               "_rid": None,
               "_cancel_reason": None,
               # streaming: the engine thread pushes ("tokens", [...])
               # chunks of NEW tokens per dispatch, then ("done", out)
               # / ("error", e); the HTTP worker drains until a
               # terminal item.  _sent tracks the high-water mark.
               # BOUNDED (lifecycle.BoundedStream): a consumer that
               # stops reading can no longer grow the queue without
               # limit — chunks drop-oldest, or ('block') the engine
               # holds this request's chunks back until the consumer
               # drains, per root.common.serve.stream_overflow.
               "stream_q": (BoundedStream(
                   self._stream_capacity, self._stream_overflow)
                   if stream else None),
               #: first monotonic ts a 'block' push found the channel
               #: full with no progress since (None = not stalled)
               "_stall_since": None,
               "_sent": 0,
               #: trace context: every flight event / span this
               #: request produces keys on "trace"; "_span" is the
               #: replica-side span children parent onto; "_phases"
               #: is the completed queue/prefill/decode decomposition
               "trace": trace, "_span": None, "_phases": None}
        with self._lock:
            if self._closed:
                raise EngineUnavailable("engine is stopped")
            rec["id"] = self._next_req_id
            self._next_req_id += 1
            self._by_id[rec["id"]] = rec
            self._ingress.append(rec)
        self._wake.set()
        if trace:
            rec["_span"] = tracing.span_add(
                trace, "replica.recv", parent=parent_span,
                req=rec["id"], prompt_len=len(prompt))
        flight.record("serve.submit", req=rec["id"],
                      prompt_len=len(prompt),
                      max_new=int(max_new), stream=bool(stream),
                      trace=trace)
        return rec

    @staticmethod
    def wait(handle):
        handle["event"].wait()
        if handle["error"] is not None:
            raise handle["error"]
        return np.asarray(handle["out"], np.int32)

    def submit(self, prompt_row, max_new, temperature=0.0, seed=0,
               adapter=0):
        """Block until this request's row finishes; returns the 1-D
        prompt+continuation array."""
        return self.wait(self.submit_async(prompt_row, max_new,
                                           temperature=temperature,
                                           seed=seed, adapter=adapter))

    def cancel(self, req_id, reason="cancelled by client"):
        """Request cancellation of an in-flight request by id (the
        ``"id"`` field of a ``submit_async`` handle).  Safe from any
        thread: the actual teardown — freeing the slot and, on paged
        pools, its KV blocks, mid-decode if needed — happens on the
        engine thread at the next loop iteration (the sole batcher
        caller).  The waiter raises RequestCancelled; a streaming
        consumer receives a terminal error chunk.  Returns True if the
        request was still live, False if unknown/already finished."""
        with self._lock:
            rec = self._by_id.get(req_id)
            if rec is None:
                return False
            if rec["_cancel_reason"] is None:
                rec["_cancel_reason"] = str(reason)
        self._cancels.append(req_id)
        self._wake.set()
        return True

    def stream_open(self, prompt_row, max_new, temperature=0.0,
                    seed=0, adapter=0, deadline_ms=None,
                    shed_exempt=False, trace=None, parent_span=None):
        """Streaming submit: returns ``(handle, iterator)`` where the
        iterator yields lists of NEW tokens per engine dispatch.  The
        submit (and thus shed/validation errors) happens EAGERLY in
        this call — the REST layer must learn about a 503/400 before
        it commits response headers; ``handle["id"]`` is the cancel
        token for a mid-stream disconnect, and ``handle["out"]`` holds
        the full result after the final chunk (authoritative even if
        drop-oldest overflow dropped mid-stream chunks)."""
        rec = self.submit_async(prompt_row, max_new,
                                temperature=temperature, seed=seed,
                                adapter=adapter, stream=True,
                                deadline_ms=deadline_ms,
                                shed_exempt=shed_exempt,
                                trace=trace, parent_span=parent_span)

        def drain():
            # chunks carry their start offset, and only CONTIGUOUS
            # progress is yielded: drop_oldest removes chunks from the
            # MIDDLE of the sequence, so anything after the first gap
            # is held back and delivered by the terminal
            # reconstruction below — concatenating the yielded chunks
            # ALWAYS equals the complete continuation exactly;
            # overflow costs incremental granularity, never tokens
            expect = 0                # next new-token index to yield
            while True:
                kind, payload = rec["stream_q"].get()
                if kind == "tokens":
                    start, toks = payload
                    if start <= expect < start + len(toks):
                        fresh = toks[expect - start:]
                        expect += len(fresh)
                        yield fresh
                elif kind == "done":
                    tail = list(payload)[len(rec["prompt"]) + expect:]
                    if tail:
                        yield tail
                    return
                else:
                    raise payload

        return rec, drain()

    def stream(self, prompt_row, max_new, temperature=0.0, seed=0,
               adapter=0, deadline_ms=None):
        """Iterator over lists of NEW tokens as they decode (one chunk
        per engine dispatch — ``ticks_per_dispatch`` tokens at a
        time), ending after the final chunk.  Raises the engine's
        error if the request fails."""
        return self.stream_open(prompt_row, max_new,
                                temperature=temperature, seed=seed,
                                adapter=adapter,
                                deadline_ms=deadline_ms)[1]

    def _finish_error(self, rec, err, kind=None, **fields):
        """Terminal error delivery: waiter raises, streaming consumer
        gets its terminal chunk, the lifecycle index forgets the id."""
        rec["error"] = err
        with self._lock:
            self._by_id.pop(rec.get("id"), None)
        if rec["stream_q"] is not None:
            self._stream_dropped += rec["stream_q"].dropped
            rec["stream_q"].put_terminal(("error", err))
        rec["event"].set()
        if kind is not None:
            flight.record(kind, req=rec.get("id"),
                          prompt_len=len(rec["prompt"]),
                          trace=rec.get("trace"), **fields)
        if rec.get("trace"):
            tracing.span_add(rec["trace"], "replica.error",
                             parent=rec.get("_span"),
                             req=rec.get("id"),
                             error=type(err).__name__)

    def _drain_cancels(self):
        """Engine thread: act on queued ``cancel()`` requests — remove
        the record wherever it currently lives (ingress, batcher
        queue, or a live slot) and free its resources."""
        while self._cancels:
            req_id = self._cancels.popleft()
            with self._lock:
                rec = self._by_id.get(req_id)
                if rec is None:
                    continue
                try:
                    self._ingress.remove(rec)
                except ValueError:
                    pass
                if rec["_rid"] is not None:
                    self._records.pop(rec["_rid"], None)
            if rec["_rid"] is not None:
                # sole-caller contract: only this thread touches the
                # batcher — frees the slot and (paged) its KV blocks
                # mid-decode
                self.cb.cancel(rec["_rid"])
            self._cancelled += 1
            admitted = rec["admit_ts"] is not None
            self._finish_error(
                rec, RequestCancelled(rec["_cancel_reason"]
                                      or "cancelled"),
                kind="serve.cancel", admitted=admitted,
                reason=rec["_cancel_reason"] or "cancelled")

    def _p50_ms_per_tok(self):
        """Measured p50 decode rate over the history window (0.0 with
        no history — never blocks admission before the first
        completions).  One O(n log n) pass; callers processing a batch
        compute it ONCE per drain, not per record."""
        with self._lock:
            vals = sorted(h["ms_per_tok"] for h in self._history)
        if not vals:
            return 0.0
        return vals[len(vals) // 2]

    def _expired(self, rec, now, p50_ms_per_tok):
        """Deadline verdict for a not-yet-admitted request: already
        past, or provably unable to finish in the remaining budget —
        predicted as the prompt's PREFILL time (measured per-token
        prefill rate x prompt length; a long prompt with a tight
        deadline 504s at submit instead of after burning its whole
        prefill) plus the decode residency (measured p50 decode rate
        x max_new).  Either estimate is 0.0 before its first
        measurement — the check never blocks a cold engine."""
        if rec["deadline"] is None:
            return False
        est_s = (p50_ms_per_tok * rec["max_new"]
                 + self._prefill_ms_per_tok
                 * len(rec["prompt"])) / 1e3
        return now >= rec["deadline"] or now + est_s > rec["deadline"]

    def _sweep_deadlines(self, now):
        """Cancel every tracked request whose deadline has passed:
        queued ones before they waste a slot, admitted ones
        mid-decode (the slot and its KV blocks free immediately)."""
        doomed = []
        with self._lock:
            for rid, rec in self._records.items():
                if rec["deadline"] is not None \
                        and now >= rec["deadline"]:
                    doomed.append((rid, rec))
            for rid, _ in doomed:
                self._records.pop(rid, None)
        for rid, rec in doomed:
            self.cb.cancel(rid)
            self._deadline_expired += 1
            admitted = rec["admit_ts"] is not None
            self._finish_error(
                rec, DeadlineExceeded(
                    "deadline expired %s (deadline_ms budget spent "
                    "%.0f ms after submit)"
                    % ("mid-decode" if admitted
                       else "before admission",
                       (now - rec["submit_ts"]) * 1e3)),
                kind="serve.deadline", admitted=admitted)

    def _update_shedder(self, now):
        """One control-loop step for the SLO shedder: the head-of-line
        wait (oldest still-unadmitted request) complements the
        per-admit measurements — it keeps the valve responsive when
        the pool is so far behind nothing is admitted at all."""
        if not self._shed.enabled:
            return
        with self._lock:
            oldest = min(
                (rec["submit_ts"] for rec in list(self._ingress)
                 + list(self._records.values())
                 if rec["admit_ts"] is None), default=None)
        head_wait_ms = (now - oldest) * 1e3 if oldest is not None \
            else 0.0
        trans = self._shed.update(head_wait_ms)
        if trans is not None:
            flight.record("serve.shed_%s" % trans,
                          head_wait_ms=round(head_wait_ms, 3),
                          slo_ms=self._slo_queue_wait_ms,
                          shed_total=self._shed.shed_total)

    def _fault_recover(self, err):
        """An engine tick raised: fail every in-flight request, hard-
        reset the batcher pool (a failed DONATED dispatch may have
        invalidated the state buffers), and keep serving — queued
        ingress requests survive and admit into the fresh pool.  The
        alternative (let the engine thread die) wedges every current
        and future waiter forever."""
        self._engine_faults += 1
        with self._lock:
            victims = list(self._records.values())
            self._records.clear()
        self.cb.reset_pool()
        for rec in victims:
            self._finish_error(
                rec, RuntimeError("engine fault failed this request: "
                                  "%r" % (err,)),
                kind="serve.fault_evict")

    def _note_prefill(self, ev):
        """Batcher prefill-observer hook (runs on the engine thread —
        the sole tick caller): one ``serve.prefill`` flight event per
        bounded chunk pass makes the admission stall visible segment
        by segment, and the measured per-token prefill rate (EWMA)
        feeds the predictive deadline check and the router's cost
        calibration surface."""
        kind = ev.get("kind")
        with self._lock:
            rec = self._records.get(ev.get("rid"))
        req = rec.get("id") if rec is not None else None
        trace = rec.get("trace") if rec is not None else None
        if kind == "segment":
            toks = int(ev.get("tokens") or 0)
            dt = float(ev.get("seconds") or 0.0)
            self._prefill_tokens += toks
            self._prefill_segments += 1
            if toks and dt > 0:
                ms_tok = dt * 1e3 / toks
                self._prefill_ms_per_tok = (
                    ms_tok if not self._prefill_ms_per_tok
                    else 0.8 * self._prefill_ms_per_tok
                    + 0.2 * ms_tok)
            flight.record("serve.prefill", req=req, phase="segment",
                          start=ev.get("start"), tokens=toks,
                          cursor=ev.get("cursor"),
                          plen=ev.get("plen"),
                          ms=round(dt * 1e3, 3), trace=trace)
        elif kind in ("begin", "admit"):
            flight.record("serve.prefill", req=req, phase=kind,
                          plen=ev.get("plen"), trace=trace)

    def _note_done(self, rec, now):
        """Completion telemetry for one request (engine thread,
        outside the lock): the ``serve.done`` flight event, the
        replica-side phase spans (queue/prefill/decode partition the
        submit→finish wall span exactly — the timeline a trace
        aggregator renders), and the per-phase latency histogram."""
        phases = rec.get("_phases") or {}
        trace = rec.get("trace")
        flight.record("serve.done", req=rec.get("id"), trace=trace,
                      new_tokens=len(rec["out"]) - len(rec["prompt"]),
                      **{"%s_ms" % k: v for k, v in phases.items()})
        if trace:
            parent = rec.get("_span")
            t = tracing.mono_to_wall(rec["submit_ts"])
            for phase in ("queue", "prefill", "decode"):
                ms = phases.get(phase)
                if ms is None:
                    continue
                tracing.span_add(trace, "phase." + phase, ts=t,
                                 dur_ms=ms, parent=parent,
                                 req=rec.get("id"))
                t += ms / 1e3
            tracing.span_add(trace, "replica.done", parent=parent,
                             ts=tracing.mono_to_wall(now),
                             req=rec.get("id"))
        try:
            from veles_tpu import telemetry
            if self._phase_hist is None:
                self._phase_hist = telemetry.registry.histogram(
                    "veles_request_phase_ms",
                    "completed-request latency decomposition "
                    "(queue/prefill/decode) in milliseconds",
                    labelnames=("phase",),
                    buckets=tracing.PHASE_BUCKETS_MS)
            for phase, ms in phases.items():
                self._phase_hist.observe(ms, phase=phase)
        except Exception:   # noqa: BLE001 — fail-soft telemetry
            pass

    def _export_serve_gauges(self, stall_ms=None):
        """Segmented-prefill registry surface (PR 3 MetricsRegistry;
        fail-soft — telemetry must never take the engine down):
        ``veles_serve_prefill_tokens_total`` /
        ``veles_serve_prefill_segments_total`` counters, the prefill
        backlog gauge, and ``veles_serve_decode_stall_ms`` — the last
        measured inter-decode-dispatch gap with rows in flight."""
        try:
            from veles_tpu import telemetry
            if self._gauges is None:
                self._gauges = {
                    "tokens": telemetry.registry.counter(
                        "veles_serve_prefill_tokens_total",
                        "prompt tokens prefilled by segmented "
                        "admission chunk passes"),
                    "segments": telemetry.registry.counter(
                        "veles_serve_prefill_segments_total",
                        "bounded admission prefill chunk passes"),
                    "backlog": telemetry.registry.gauge(
                        "veles_serve_prefill_backlog_tokens",
                        "queued-but-unprefilled prompt tokens"),
                    "stall": telemetry.registry.gauge(
                        "veles_serve_decode_stall_ms",
                        "inter-decode-dispatch gap with streams in "
                        "flight (the admission stall)"),
                    "_tokens_seen": 0, "_segments_seen": 0,
                }
            d_tok = self._prefill_tokens - self._gauges["_tokens_seen"]
            if d_tok > 0:
                self._gauges["tokens"].inc(d_tok)
                self._gauges["_tokens_seen"] = self._prefill_tokens
            d_seg = (self._prefill_segments
                     - self._gauges["_segments_seen"])
            if d_seg > 0:
                self._gauges["segments"].inc(d_seg)
                self._gauges["_segments_seen"] = self._prefill_segments
            self._gauges["backlog"].set(self._prefill_backlog)
            if stall_ms is not None:
                self._gauges["stall"].set(round(stall_ms, 3))
        except Exception:   # noqa: BLE001 — fail-soft
            pass

    def _loop(self):
        while True:
            with self._lock:
                if self._closed:
                    return
                new = list(self._ingress)
                self._ingress.clear()
            now = time.monotonic()
            p50_ms = self._p50_ms_per_tok() if new else 0.0
            for rec in new:           # engine thread: sole cb caller
                if rec["_cancel_reason"] is not None:
                    continue          # cancel arrived pre-submit —
                                      # _drain_cancels below delivers
                if self._expired(rec, now, p50_ms):
                    with self._lock:
                        self._by_id.pop(rec.get("id"), None)
                    self._deadline_expired += 1
                    self._finish_error(
                        rec, DeadlineExceeded(
                            "deadline expired before admission"),
                        kind="serve.deadline", admitted=False)
                    continue
                try:
                    rid = self.cb.submit(rec["prompt"], rec["max_new"],
                                         adapter=rec.get("adapter", 0),
                                         temperature=rec["temperature"],
                                         seed=rec["seed"])
                except Exception as e:  # noqa: BLE001 — deliver to waiter
                    self._finish_error(rec, e)
                    continue
                stopped = False
                with self._lock:
                    if self._closed:   # stop() raced the hand-off
                        stopped = True
                    else:
                        rec["_rid"] = rid
                        self._records[rid] = rec
                if stopped:           # release the waiter
                    self._finish_error(rec, RuntimeError(
                        "engine stopped before request completed"))
            self._drain_cancels()
            now = time.monotonic()
            self._sweep_deadlines(now)
            self._update_shedder(now)
            if self.cb.idle():
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            with self._lock:
                self.cb.stream_partials = any(
                    rec["stream_q"] is not None
                    for rec in self._records.values())
            tick_start = time.monotonic()
            try:
                n_active = self.cb.tick()   # device dispatch — NO lock
            except Exception as e:    # noqa: BLE001 — survive the tick
                flight.record("serve.engine_fault", error=repr(e))
                self._fault_recover(e)
                self._had_active = False
                continue
            now = time.monotonic()
            # decode-tick cadence: the gap between consecutive
            # dispatch completions while rows were decoding across the
            # boundary — the inter-chunk gap a streaming client sees.
            # Whole-prompt admissions inflate its p99; the segmented
            # prefill budget bounds it (metrics p50/p99_decode_stall).
            stall_ms = None
            if self._had_active and self._last_tick_end is not None:
                stall_ms = (now - self._last_tick_end) * 1e3
                self._stall_hist.append(stall_ms)
            self._last_tick_end = now
            self._had_active = bool(n_active)
            active = self.cb.active_requests()
            done = []
            pushes = []
            with self._lock:
                for rid, rec in self._records.items():
                    admitted = rid in active or \
                        self.cb.result(rid) is not None
                    if rec["admit_ts"] is None and admitted:
                        # admission happened in THIS tick's admit phase
                        # — stamp its start, so a request that also
                        # finishes within the tick (short max_new,
                        # fused dispatch) records the tick's real
                        # duration as decode time, not a 1e-9 floor
                        rec["admit_ts"] = tick_start
                        qw_ms = (tick_start - rec["submit_ts"]) * 1e3
                        # the MEASURED queue wait: the flight event is
                        # the post-mortem record, the shedder feed is
                        # the closed loop acting on the same number
                        self._shed.note_admit(qw_ms)
                        # flight gets the REAL admission (serve.submit
                        # marked the enqueue): the gap between the two
                        # is the queue wait a post-mortem measures
                        flight.record(
                            "serve.admit", req=rec.get("id"),
                            prompt_len=len(rec["prompt"]),
                            queue_wait_ms=qw_ms,
                            trace=rec.get("trace"))
                for rid, rec in self._records.items():
                    if rec["stream_q"] is None:
                        continue
                    part = self.cb.partial(rid)
                    if part is None:
                        continue
                    # _sent advances only on DELIVERY below: a chunk a
                    # full 'block' channel refuses is re-derived from
                    # the next dispatch's partial instead of lost
                    fresh = part[len(rec["prompt"]) + rec["_sent"]:]
                    if fresh:
                        pushes.append((rec, fresh))
                for rid in list(self._records):
                    out = self.cb.pop_result(rid)
                    if out is None:
                        continue
                    rec = self._records.pop(rid)
                    self._by_id.pop(rec.get("id"), None)
                    rec["out"] = out
                    done.append(rec)
                    admit = rec["admit_ts"] or now
                    dec = max(1e-9, now - admit)
                    n_new = len(out) - len(rec["prompt"])
                    qw_ms = (admit - rec["submit_ts"]) * 1e3
                    rec["_queue_wait_ms"] = qw_ms
                    # phase decomposition: the batcher stamped when
                    # this row's FIRST decode dispatch went out, so
                    # the admitted→finished residency splits into the
                    # prefill share (admission chunk passes) and the
                    # pure decode share — non-overlapping by
                    # construction (they partition [admit, now])
                    ds = self.cb.pop_decode_start(rid)
                    if ds is None or not admit <= ds <= now:
                        ds = admit
                    prefill_ms = (ds - admit) * 1e3
                    pure_ms = max(0.0, (now - ds) * 1e3)
                    rec["_phases"] = {
                        "queue": round(qw_ms, 3),
                        "prefill": round(prefill_ms, 3),
                        "decode": round(pure_ms, 3)}
                    self._history.append({
                        "queue_wait_ms": qw_ms,
                        "decode_ms": dec * 1e3,
                        "prefill_ms": prefill_ms,
                        "pure_decode_ms": pure_ms,
                        "new_tokens": n_new,
                        "tokens_per_sec": n_new / dec,
                        "ms_per_tok": dec * 1e3 / max(1, n_new),
                        "finish_ts": now})
                    self._served += 1
            # stream delivery: push is NON-blocking (one slow consumer
            # must never freeze the engine loop every other request's
            # decode shares).  A full 'block' channel keeps this
            # request's chunks back for the next dispatch; once it has
            # made no progress for stream_stall_timeout_ms the
            # consumer is dead or a slowloris — cancel the request
            # instead of letting it pin its slot.
            for rec, fresh in pushes:
                if rec["stream_q"].push(
                        ("tokens", (rec["_sent"], fresh))):
                    rec["_sent"] += len(fresh)
                    rec["_stall_since"] = None
                elif rec["_stall_since"] is None:
                    rec["_stall_since"] = now
                elif now - rec["_stall_since"] > self._stream_stall_s:
                    flight.record("serve.stream_stall",
                                  req=rec.get("id"),
                                  sent=rec["_sent"])
                    self.cancel(rec["id"],
                                reason="stream consumer stalled past "
                                       "stream_stall_timeout_ms")
            if self._kv_gauge is not None:
                with self._lock:
                    self._kv_gauge = self.cb.free_blocks()
                    if self._prefix_gauge is not None:
                        self._prefix_gauge = self.cb.prefix_stats()
            # prefill-backlog snapshot (engine thread — the batcher's
            # queue/staging are tick-caller state) + registry gauges
            self._prefill_backlog = self.cb.prefill_backlog_tokens()
            self._export_serve_gauges(stall_ms)
            for rec in done:          # wake waiters outside the lock
                self._note_done(rec, now)
                if self._slo_queue_wait_ms and \
                        rec.get("_queue_wait_ms", 0.0) \
                        > self._slo_queue_wait_ms:
                    flight.record(
                        "serve.slo_breach", req=rec.get("id"),
                        queue_wait_ms=rec["_queue_wait_ms"],
                        slo_ms=self._slo_queue_wait_ms,
                        prompt_len=len(rec["prompt"]),
                        trace=rec.get("trace"))
                if rec["stream_q"] is not None:
                    # no tail flush here: the terminal's payload IS the
                    # full result, and the consumer-side drain yields
                    # whatever the last dispatch decoded (or overflow
                    # swallowed) as one final reconstructed chunk —
                    # a full 'block' channel at completion can refuse
                    # nothing it would lose
                    self._stream_dropped += rec["stream_q"].dropped
                    rec["stream_q"].put_terminal(("done", rec["out"]))
                rec["event"].set()

    def metrics(self):
        """Serving-plane SLO snapshot: queue depth, in-flight rows,
        served count, p50/p99 queue-wait and per-stream decode rate
        over the last ``history`` completed requests."""
        with self._lock:
            hist = list(self._history)
            queued = len(self._ingress) + sum(
                1 for r in self._records.values()
                if r["admit_ts"] is None)
            in_flight = sum(1 for r in self._records.values()
                            if r["admit_ts"] is not None)
            served = self._served
            # prompts still in the HTTP ingress have not reached the
            # batcher's queue — they are prefill backlog too
            ingress_toks = sum(len(r["prompt"]) for r in self._ingress)
            stalls = list(self._stall_hist)
        out = {"served": served, "queued": queued,
               "in_flight": in_flight, "slots": self.cb.slots,
               "uptime_s": round(time.monotonic() - self._start_ts, 1),
               "agg_tokens_per_sec": 0.0,
               # lifecycle counters (docs/services.md "Serving
               # robustness"): shed valve state + how many requests
               # each enforcement path has taken out
               "shed_state": self._shed.status()["state"],
               "shed_total": self._shed.shed_total,
               "cancelled_total": self._cancelled,
               "deadline_expired_total": self._deadline_expired,
               "engine_faults": self._engine_faults,
               "stream_dropped_chunks": self._stream_dropped,
               # segmented-prefill surface (docs/services.md
               # "Disaggregated prefill"): backlog in TOKENS (the
               # autoscaler's early signal), work done, measured rate
               "queued_prefill_tokens": (ingress_toks
                                         + self._prefill_backlog),
               "prefill_tokens_total": self._prefill_tokens,
               "prefill_segments_total": self._prefill_segments,
               "prefill_ms_per_tok": round(self._prefill_ms_per_tok,
                                           4)}
        if self._kv_gauge is not None:
            out["free_kv_blocks"] = self._kv_gauge
        if self._prefix_gauge is not None:
            out["prefix_shared_blocks"] = self._prefix_gauge[0]
            out["prefix_block_refs"] = self._prefix_gauge[1]

        def pct(vals, q):
            if not vals:
                return 0.0
            vals = sorted(vals)
            return round(vals[min(len(vals) - 1,
                                  int(q / 100.0 * len(vals)))], 3)

        # per-phase decomposition percentiles (docs/services.md
        # "Request tracing"): queue_wait/prefill/pure_decode partition
        # each request's submit→finish span, so the phase a latency
        # miss lives in reads straight off the metrics
        for key in ("queue_wait_ms", "ms_per_tok", "tokens_per_sec",
                    "prefill_ms", "pure_decode_ms"):
            vals = [h[key] for h in hist if key in h]
            out["p50_" + key] = pct(vals, 50)
            out["p99_" + key] = pct(vals, 99)
        # span-store eviction count (the bounded trace ring's
        # "counted gauge"; also veles_trace_dropped_total)
        out["trace_dropped_total"] = tracing.store.dropped
        # the decode-tick cadence: inter-dispatch gap with streams in
        # flight — whole-prompt admissions inflate its p99, segmented
        # prefill bounds it (the stall-free serving gate's number)
        out["p50_decode_stall_ms"] = pct(stalls, 50)
        out["p99_decode_stall_ms"] = pct(stalls, 99)
        if len(hist) >= 2:
            # pool-level throughput: all new tokens in the history
            # window over the window's wall span (concurrent streams
            # overlap — summing per-stream decode times would undercount)
            span = hist[-1]["finish_ts"] - hist[0]["finish_ts"]
            if span > 1e-9:
                out["agg_tokens_per_sec"] = round(
                    sum(h["new_tokens"] for h in hist[1:]) / span, 1)
        return out

    def reset_metrics(self):
        """Clear the latency history and served counter (e.g. after a
        warmup request whose first-dispatch compile time would pollute
        the percentiles)."""
        with self._lock:
            self._history.clear()
            self._stall_hist.clear()
            self._served = 0
            self._start_ts = time.monotonic()

    def lifecycle_status(self):
        """The ``/api/health`` serving block: shed valve state plus
        the lifecycle counters — cheap and lock-light, safe for a
        liveness probe."""
        out = dict(self._shed.status())
        with self._lock:
            out.update({
                "open_requests": len(self._by_id),
                "cancelled_total": self._cancelled,
                "deadline_expired_total": self._deadline_expired,
                "engine_faults": self._engine_faults,
                "stream_dropped_chunks": self._stream_dropped,
            })
        return out

    def leak_check(self):
        """Post-drain resource audit for the chaos harness and the
        lifecycle tests: call AFTER the engine went idle (metrics()
        queued == in_flight == 0) — it reads batcher state that only
        the engine thread may touch while work is in flight.  Every
        value should be 0 / True on a healthy drained engine."""
        with self._lock:
            out = {"ingress": len(self._ingress),
                   "records": len(self._records),
                   "open_requests": len(self._by_id),
                   "pending_cancels": len(self._cancels)}
        out["slots_busy"] = sum(
            1 for r in self.cb._slot_req if r is not None)
        if hasattr(self.cb, "free_blocks"):
            out["kv_blocks_leaked"] = (self.cb.pool_blocks
                                       - self.cb.free_blocks())
        out["engine_thread_alive"] = self._thread.is_alive()
        return out

    def stop(self):
        with self._lock:
            self._closed = True
            # release every waiter: queued records error out, in-flight
            # ones too (wait() raises instead of hanging forever)
            pending = list(self._ingress) + list(self._records.values())
            self._ingress.clear()
            self._records.clear()
            self._by_id.clear()
        self._cancels.clear()
        for rec in pending:
            if rec["out"] is None and rec["error"] is None:
                rec["error"] = RuntimeError(
                    "engine stopped before request completed")
            if rec.get("stream_q") is not None and rec["out"] is None:
                # a streaming consumer blocks in stream_q.get(), not on
                # the event — it needs its own terminal or it hangs
                rec["stream_q"].put_terminal(("error", rec["error"]))
            rec["event"].set()
        self._wake.set()
        self._thread.join(timeout=5)


class RESTfulAPI(Logger):
    def __init__(self, forward, input_shape, host="127.0.0.1", port=8180,
                 path="/service", generator=None, batch_window=0.0,
                 max_batch=8, continuous_slots=0, paged_block=0,
                 pool_tokens=None, prefix_cache=False,
                 speculative_k=0, ticks_per_dispatch=1,
                 prefill_segment=None):
        super(RESTfulAPI, self).__init__()
        self.forward = forward            # callable(np.ndarray) -> ndarray
        self.input_shape = tuple(input_shape)
        self.host, self.port, self.path = host, port, path
        #: models.generate.LMGenerator — enables the ``"generate"``
        #: request form for causal-LM workflows
        self.generator = generator
        #: batch_window > 0: coalesce concurrent generate requests into
        #: one device call (GenerateBatcher)
        self.batcher = (GenerateBatcher(generator, batch_window,
                                        max_batch)
                        if generator is not None and batch_window > 0
                        else None)
        #: continuous_slots > 0: in-flight batching — requests join the
        #: live decode at the next tick (ContinuousEngine; greedy and
        #: plain-temperature requests only, top_k/top_p/beam/speculative
        #: fall through to the other paths)
        self.engine = (ContinuousEngine(generator, continuous_slots,
                                        paged_block=paged_block,
                                        pool_tokens=pool_tokens,
                                        prefix_cache=prefix_cache,
                                        speculative_k=speculative_k,
                                        ticks_per_dispatch=
                                        ticks_per_dispatch,
                                        prefill_segment=prefill_segment)
                       if generator is not None and continuous_slots > 0
                       else None)
        self._server = None
        self._thread = None
        #: graceful-shutdown state machine (services.lifecycle): while
        #: not "serving", the work endpoint rejects new requests with
        #: 503 + Retry-After, in-flight ones finish, and {path}/health
        #: reports the drain state so a fleet router stops routing here
        self.drain_state = DrainState()
        self._drain_thread = None
        #: in-flight work-endpoint POSTs (admission through response
        #: written) — the drain watcher's "finished in-flight" signal;
        #: engine/batcher queue depths alone miss the tail between a
        #: request leaving the pool and its response hitting the socket
        self._http_inflight = 0
        self._http_lock = threading.Lock()

    # ------------------------------------------------------------- server
    def start(self):
        api = self

        class Handler(BaseHTTPRequestHandler):
            def _send_json(self, code, payload, headers=()):
                send_json(self, code, payload, headers)

            def do_GET(self):
                if self.path == api.path + "/metrics":
                    self._send_json(200, api.serving_metrics())
                elif self.path == api.path + "/health":
                    # the fleet router's probe surface: drain state +
                    # the PR 6 lifecycle block.  503 while not serving
                    # so dumb LBs also stop sending traffic here.
                    state = api.health_status()
                    self._send_json(
                        200 if state["state"] == "serving" else 503,
                        state)
                elif self.path == api.path + "/leaks":
                    # post-drain resource audit (chaos harness; call
                    # once idle — see ContinuousEngine.leak_check)
                    self._send_json(200, api.engine.leak_check()
                                    if api.engine is not None else {})
                elif self.path.startswith(api.path + "/trace/"):
                    # this replica's spans for one trace id (the
                    # router's /trace/<id> aggregation fans out here;
                    # 404 = unknown or already ring-evicted)
                    tid = self.path[len(api.path + "/trace/"):]
                    spans = tracing.store.spans(tid)
                    self._send_json(
                        200 if spans else 404,
                        {"trace": tid, "spans": spans,
                         "phases": tracing.phases_of(spans)})
                else:
                    self.send_error(404)

            def do_POST(self):
                if self.path == api.path + "/drain":
                    # admin drain: stop admission, finish in-flight,
                    # report "drained" on /health.  202: the drain is
                    # accepted and proceeds in the background.
                    api.drain(reason="admin /drain")
                    self._send_json(202, api.drain_state.status())
                    return
                if self.path != api.path:
                    self.send_error(404)
                    return
                # count FIRST, then check the drain gate: the drain
                # watcher polls the counter, so a request that passed
                # the gate is always visible to it (no slip-through
                # between check and increment)
                with api._http_lock:
                    api._http_inflight += 1
                try:
                    if not api.drain_state.is_serving():
                        ra = api.drain_retry_after_s()
                        self._send_json(
                            503,
                            {"error": "draining: this endpoint is "
                                      "not admitting new work",
                             "draining": True, "retry_after_s": ra},
                            headers=[("Retry-After",
                                      str(max(1, int(math.ceil(ra)))))])
                        return
                    self._do_work_post()
                finally:
                    with api._http_lock:
                        api._http_inflight -= 1

            def _do_work_post(self):
                # trace context (telemetry.tracing): a fleet router
                # upstream supplies it on the X-Veles-Trace header;
                # serving bare, THIS replica is the edge and mints —
                # an absent/forged header value mints fresh too, so a
                # client can never pick its own id here either
                ctx = tracing.parse_header(
                    self.headers.get(tracing.TRACE_HEADER))
                minted = ctx is None
                if minted:
                    trace = tracing.new_trace_id()
                    parent = tracing.span_add(trace, "request",
                                              edge="replica")
                else:
                    trace, parent = ctx
                t_edge = time.monotonic()
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length))
                    if isinstance(req.get("generate"), dict) and \
                            req["generate"].get("stream"):
                        # NDJSON streaming: one {"tokens": [...]} line
                        # per engine dispatch, then {"done", "result"}.
                        # HTTP/1.0 semantics — body is EOF-delimited,
                        # so no Content-Length / chunking needed.
                        # run_generate_stream submits EAGERLY, so
                        # shed (503) / validation (400) surface before
                        # the 200 header commits.
                        prompt, chunks, handle = \
                            api.run_generate_stream(
                                req, trace=trace, parent_span=parent)
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/x-ndjson")
                        self.end_headers()
                        got = list(prompt)
                        # headers are out: a mid-stream ENGINE failure
                        # surfaces as a structured NDJSON error line;
                        # a failed WRITE means the client is gone —
                        # cancel engine-side so the request frees its
                        # slot (and KV blocks) instead of decoding to
                        # completion for nobody.
                        try:
                            for fresh in chunks:
                                got.extend(fresh)
                                self.wfile.write(
                                    (json.dumps({"tokens": fresh})
                                     + "\n").encode())
                                self.wfile.flush()
                            t_done = time.monotonic()
                            # the handle's final result is authoritative
                            # even if drop-oldest overflow dropped
                            # mid-stream chunks on a slow reader
                            result = (list(handle["out"])
                                      if handle["out"] is not None
                                      else got)
                            # the terminal line carries the trace id
                            # (the client's reconstruction key) and
                            # the engine's phase decomposition (the
                            # router's fleet rollup reads it here)
                            tail = {"done": True, "result": result,
                                    "trace": trace}
                            if handle.get("_phases"):
                                tail["phases"] = handle["_phases"]
                            dropped = (handle["stream_q"].dropped
                                       if handle["stream_q"] is not None
                                       else 0)
                            if dropped:
                                tail["dropped_chunks"] = dropped
                            self.wfile.write(
                                (json.dumps(tail) + "\n").encode())
                            # stream phase: engine completion → final
                            # line written (the delivery tail the
                            # engine phases cannot see).  Only when
                            # THIS replica is the edge — behind a
                            # router the stream remainder is the
                            # router's to attribute, and recording it
                            # twice would double-count the phase
                            if minted:
                                tracing.span_add(
                                    trace, "phase.stream",
                                    parent=(handle.get("_span")
                                            or parent),
                                    dur_ms=round((time.monotonic()
                                                  - t_done) * 1e3, 3),
                                    req=handle.get("id"))
                        except Exception as e:  # noqa: BLE001
                            api.engine.cancel(
                                handle["id"],
                                reason="stream write failed: %r" % e)
                            try:
                                # "kind" lets a fleet router tell a
                                # REQUEST-scoped terminal (deadline,
                                # cancel — relay to the client, the
                                # replica is healthy) from an ENGINE-
                                # scoped one (fail over)
                                self.wfile.write(
                                    (json.dumps(
                                        {"error": str(e),
                                         "kind": type(e).__name__})
                                     + "\n").encode())
                            except Exception:  # noqa: BLE001 — dead pipe
                                pass
                        return
                    meta = {}
                    if "generate" in req:
                        out = api.run_generate(req, trace=trace,
                                               parent_span=parent,
                                               meta=meta)
                    else:
                        out = np.asarray(api.forward(api.decode_input(req)))
                    payload = {"result": out.tolist(), "trace": trace}
                    if meta.get("phases"):
                        payload["phases"] = meta["phases"]
                    self._send_json(200, payload)
                except ShedError as e:
                    # SLO admission shedding: tell the client to back
                    # off instead of queuing into a breach
                    self._send_json(
                        503, {"error": str(e),
                              "retry_after_s": e.retry_after_s},
                        headers=[("Retry-After", str(max(
                            1, int(math.ceil(e.retry_after_s)))))])
                except EngineUnavailable as e:
                    # a stopped engine is service unavailability, not
                    # a bad request: 503 so a fleet router routes
                    # around this replica instead of failing the
                    # client with a "deterministic" 400
                    self._send_json(
                        503, {"error": str(e), "retry_after_s": 1.0},
                        headers=[("Retry-After", "1")])
                except DeadlineExceeded as e:
                    self._send_json(504, {"error": str(e)})
                except Exception as e:  # noqa: BLE001 — report to client
                    self._send_json(400, {"error": str(e)})
                finally:
                    if minted:
                        # terminal-span rule: the edge that MINTED the
                        # id terminates the trace — exactly once, on
                        # every exit path (success, error, dead pipe)
                        tracing.span_add(
                            trace, "request.done", parent=parent,
                            terminal=True,
                            dur_ms=round((time.monotonic()
                                          - t_edge) * 1e3, 3))

            def log_message(self, fmt, *args):
                api.debug("http: " + fmt, *args)

        class Server(ThreadingHTTPServer):
            # socketserver's default listen backlog of 5 resets
            # connections under a concurrent client burst
            request_queue_size = 128

        self._server = Server((self.host, self.port), Handler)
        self.port = self._server.server_address[1]   # resolve port 0
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        self.info("serving on http://%s:%d%s", self.host, self.port,
                  self.path)

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server = None
        if self.batcher is not None:
            self.batcher.stop()
        if self.engine is not None:
            self.engine.stop()

    # -------------------------------------------------------------- drain
    def drain(self, reason="drain"):
        """Graceful shutdown, phase 1: stop admitting (the work
        endpoint 503s with Retry-After), let every in-flight request
        finish, then flip ``drain_state`` to ``drained`` (watched by
        :meth:`wait_drained`, ``{path}/health``, and the fleet
        router).  Idempotent; returns True on the serving→draining
        transition.  The endpoint itself stays up — a drained replica
        still answers health probes until its owner calls
        :meth:`stop` / exits."""
        if not self.drain_state.begin(reason):
            return False
        flight.record("serve.drain", pid=os.getpid(),
                      reason=str(reason))
        self._drain_thread = threading.Thread(
            target=self._drain_watch, name="VelesDrain", daemon=True)
        self._drain_thread.start()
        return True

    def wait_drained(self, timeout=None):
        """Block until every in-flight request finished (True) or
        ``timeout`` passed (False)."""
        return self.drain_state.wait("drained", timeout=timeout)

    def drain_retry_after_s(self):
        """Retry-After hint for requests refused while draining: one
        shedder window when an SLO is configured (same backoff the
        overload path hands out), else one second."""
        if self.engine is not None:
            return self.engine._shed.retry_after_s()
        return 1.0

    def _idle(self):
        """True iff no request is anywhere in the serving pipeline:
        engine queue/pool empty, coalescer empty, and every work POST
        has written its response."""
        with self._http_lock:
            if self._http_inflight:
                return False
        if self.engine is not None:
            m = self.engine.metrics()
            if m["queued"] or m["in_flight"]:
                return False
        if self.batcher is not None and self.batcher.pending():
            return False
        return True

    def _drain_watch(self):
        from veles_tpu.config import root
        timeout_s = float(root.common.serve.get(
            "drain_timeout_ms", 30000)) / 1e3
        deadline = time.monotonic() + timeout_s
        forced = False
        while not self._idle():
            if time.monotonic() >= deadline:
                forced = True
                break
            time.sleep(0.02)
        self.drain_state.finish()
        flight.record("serve.drained", pid=os.getpid(),
                      forced=forced)
        if forced:
            self.warning("drain forced through after %.1f s with "
                         "requests still in flight "
                         "(root.common.serve.drain_timeout_ms)",
                         timeout_s)

    def health_status(self):
        """``{path}/health`` payload: drain state + the PR 6 lifecycle
        block + queue-depth vitals — everything the fleet router's
        probe needs in one cheap GET.  A dead ENGINE thread (stopped,
        or killed by something the fault-recovery path could not
        survive) reports ``"failed"`` even though HTTP still answers —
        a router must not route work into a serving shell whose pool
        no longer ticks."""
        state = self.drain_state.state
        if self.engine is not None and state == "serving" \
                and not self.engine._thread.is_alive():
            state = "failed"
        out = {"state": state, "pid": os.getpid(),
               "port": self.port}
        if self.drain_state.since is not None:
            out["drain"] = self.drain_state.status()
        if self.engine is not None:
            try:
                out["serving"] = self.engine.lifecycle_status()
                m = self.engine.metrics()
                # queued_prefill_tokens: the fleet autoscaler's early
                # scale-up signal (prefill backlog predicts the queue-
                # wait breach); the measured rates feed the router's
                # cost-weighted placement calibration
                for key in ("queued", "in_flight", "served", "slots",
                            "queued_prefill_tokens", "p50_ms_per_tok",
                            "prefill_ms_per_tok"):
                    out[key] = m[key]
            except Exception as e:  # noqa: BLE001 — probe never 500s
                out["serving"] = {"error": str(e)}
        return out

    def serving_metrics(self):
        """GET ``{path}/metrics``: the serving plane's SLO surface —
        ContinuousEngine latency percentiles when the slot pool is on,
        plus which serving paths are active."""
        out = {"paths": {
            "continuous": self.engine is not None,
            "coalescing": self.batcher is not None,
            "generate": self.generator is not None}}
        if self.engine is not None:
            out["continuous"] = self.engine.metrics()
        return out

    # ---------------------------------------------------------- generation
    @staticmethod
    def _engine_opts_subset(opts):
        """The sampling-options subset EVERY slot-pool path requires:
        no top-k/top-p truncation (the pool decodes greedy/plain-
        temperature only) and at least one new token (a slot must
        decode something to ever free itself).  The engine dispatch
        branch checks exactly this — beam/speculative requests were
        dispatched before it runs — while the adapter and streaming
        gates layer the beam/speculative exclusions on top via
        ``_plain_engine_request``."""
        return (int(opts.get("top_k", 0)) == 0
                and float(opts.get("top_p", 1.0)) >= 1.0
                and int(opts.get("max_new", 16)) >= 1)

    @staticmethod
    def _plain_engine_request(opts):
        """True iff this generate request can ride the slot pool from
        a cold start: plain greedy/temperature, no beam, no
        speculative — the predicate the adapter gate and the streaming
        gate share (the engine dispatch branch needs only
        ``_engine_opts_subset``; see there)."""
        return (int(opts.get("beam", 0)) <= 1
                and not int(opts.get("speculative", 0))
                and RESTfulAPI._engine_opts_subset(opts))

    def run_generate_stream(self, req, trace=None, parent_span=None):
        """NDJSON token streaming: validates a single-row greedy /
        plain-temperature engine request and returns (prompt, iterator
        over new-token chunks, engine handle).  The submit happens
        EAGERLY — shed/validation errors raise here, before the
        HTTP layer commits response headers — and the handle carries
        the cancel token (``handle["id"]``) for a mid-stream
        disconnect plus the authoritative final result
        (``handle["out"]``).  Everything else must use the buffered
        endpoint — streaming has no batch to coalesce and no beam
        state to surface incrementally."""
        if self.generator is None:
            raise ValueError("this endpoint serves a non-LM workflow: "
                             "no generator is attached")
        opts = req.get("generate")
        if not isinstance(opts, dict):
            raise ValueError("'generate' must be an options object")
        if self.engine is None:
            raise ValueError("\"stream\" requires the continuous "
                             "engine (continuous_slots>0)")
        prompt = np.asarray(req["input"], np.int32)
        if prompt.ndim == 1:
            prompt = prompt[None]
        if prompt.shape[0] != 1:
            raise ValueError("\"stream\" serves ONE row per request")
        if not self._plain_engine_request(opts):
            raise ValueError("\"stream\" supports plain greedy/"
                             "temperature requests only")
        self.generator.validate_request(len(prompt[0]), opts)
        handle, it = self.engine.stream_open(
            prompt[0], int(opts.get("max_new", 16)),
            temperature=float(opts.get("temperature", 0.0)),
            seed=int(opts.get("seed", 0)),
            adapter=int(opts.get("adapter", 0)),
            deadline_ms=opts.get("deadline_ms"),
            # {"resume": true}: a fleet router relocating an already-
            # admitted stream off a dead replica — exempt from the
            # shed valve (see submit_async), never from validation
            shed_exempt=bool(req.get("resume")),
            trace=trace, parent_span=parent_span)
        return prompt[0].tolist(), it, handle

    def run_generate(self, req, trace=None, parent_span=None,
                     meta=None):
        """``{"input": [[tok, ...]], "generate": {"max_new": N,
        "temperature": T, "seed": S}}`` → generated token matrix (causal
        LM serving; needs ``generator=``).  ``trace``/``parent_span``
        thread the request's trace context into the slot pool;
        ``meta`` (a dict, mutated) receives the engine's phase
        decomposition for the HTTP layer to embed in the response."""
        if self.generator is None:
            raise ValueError("this endpoint serves a non-LM workflow: "
                             "no generator is attached")
        opts = req.get("generate")
        if not isinstance(opts, dict):
            # null/false/0/[] must not silently mean "generate with
            # defaults" — only an options object selects this endpoint
            raise ValueError(
                "'generate' must be an options object like "
                "{\"max_new\": 16}, got %r" % (opts,))
        prompt = np.asarray(req["input"], np.int32)
        if prompt.ndim == 1:
            prompt = prompt[None]
        if int(opts.get("adapter", 0)) and (
                self.engine is None
                or not self._plain_engine_request(opts)):
            # adapter routing lives in the slot pool's tick; every
            # other path runs un-adapted params and would silently
            # serve the base model
            raise ValueError("\"adapter\" routing requires the "
                             "continuous engine (continuous_slots>0) "
                             "and a plain greedy/temperature request")
        beam = int(opts.get("beam", 0))
        if beam > 1:
            out, _ = self.generator.beam_search(
                prompt, int(opts.get("max_new", 16)), beam=beam)
            return out
        spec = int(opts.get("speculative", 0))
        if (spec and prompt.shape[0] == 1 and self.batcher is None
                and float(opts.get("temperature", 0.0)) == 0.0):
            # greedy single-row requests can opt into in-jit n-gram
            # speculation (exact greedy semantics; generate_speculative
            # falls back itself when speculation can't apply)
            return self.generator.generate_speculative(
                prompt, int(opts.get("max_new", 16)), draft_k=spec)
        if self.engine is not None and self._engine_opts_subset(opts):
            # (beam/speculative were dispatched above; a speculative
            # request that fell through — batcher attached, sampled,
            # or multi-row — rides the pool as plain decode, as
            # before.  max_new=0 echo/score requests fall through —
            # the solo and coalescing paths serve them; the slot pool
            # can't)
            for row in prompt:
                self.generator.validate_request(len(row), opts)
            handles = []
            try:
                for row in prompt:
                    handles.append(self.engine.submit_async(
                        row, int(opts.get("max_new", 16)),
                        temperature=float(opts.get("temperature", 0.0)),
                        seed=int(opts.get("seed", 0)),
                        adapter=int(opts.get("adapter", 0)),
                        deadline_ms=opts.get("deadline_ms"),
                        # a resume relocation keeps its exemption on
                        # the buffered path too (prefill handoffs ride
                        # it); the trace context rides along so the
                        # relocated leg joins the original timeline
                        shed_exempt=bool(req.get("resume")),
                        trace=trace, parent_span=parent_span))
            except ShedError:
                # the shedder opened mid-request: the rows already in
                # must not decode for a client that gets a 503
                for h in handles:
                    self.engine.cancel(h["id"],
                                       reason="sibling row shed")
                raise
            out = np.stack([self.engine.wait(h) for h in handles])
            if meta is not None and handles[0].get("_phases"):
                meta["phases"] = handles[0]["_phases"]
            return out
        if self.batcher is not None:
            # validate THIS request up front — a bad one must 400 alone,
            # never poison the batch it would have coalesced into
            for row in prompt:
                self.generator.validate_request(len(row), opts)
            # coalesce with whatever else is in flight; a request's
            # rows share its opts, outputs re-stack to the input shape
            # (enqueue every row BEFORE waiting so one request's rows
            # ride a single batch)
            slots = [self.batcher.submit_async(row, opts)
                     for row in prompt]
            return np.stack([self.batcher.wait(s) for s in slots])
        return self.generator.generate(
            prompt, int(opts.get("max_new", 16)),
            temperature=float(opts.get("temperature", 0.0)),
            seed=int(opts.get("seed", 0)),
            top_k=int(opts.get("top_k", 0)),
            top_p=float(opts.get("top_p", 1.0)))

    # ------------------------------------------------------------ decoding
    def decode_input(self, req):
        """codec 'list' (default): nested lists; codec 'base64': raw
        float32 little-endian bytes with explicit shape (ref restful
        input contract)."""
        codec = req.get("codec", "list")
        if codec == "base64":
            raw = base64.b64decode(req["input"])
            shape = tuple(req.get("shape") or (-1,) + self.input_shape)
            x = np.frombuffer(raw, dtype=np.float32).reshape(shape)
        elif codec == "list":
            x = np.asarray(req["input"], np.float32)
        else:
            raise ValueError("unknown codec %r" % codec)
        if x.ndim == len(self.input_shape):   # single sample
            x = x[None]
        expect = x.shape[1:]
        if tuple(expect) != self.input_shape and \
                int(np.prod(expect)) != int(np.prod(self.input_shape)):
            raise ValueError("input shape %s incompatible with %s"
                             % (expect, self.input_shape))
        return x.reshape((len(x),) + self.input_shape)


#: the fleet READY handshake: a replica process announces its bound
#: port on stdout with this prefix, and whoever spawned it (a pod
#: agent, tools/chaos_common.spawn_ready) reads the line to learn
#: where to register it.  One spelling everywhere — the agent, the
#: chaos harnesses and `--serve` must not drift.
READY_LINE = "REPLICA_READY"


def announce_ready(api, force=False, stream=None):
    """Print the fleet READY handshake line for a started
    :class:`RESTfulAPI` (``REPLICA_READY port=<p> pid=<pid>``).  By
    default it only fires when ``VELES_TPU_REPLICA_ANNOUNCE`` is set
    in the environment — the pod agent sets it on every replica it
    spawns, so any serving command (``python -m veles_tpu ...
    --serve 0``) becomes a fleet replica without a dedicated entry
    point; pass ``force=True`` for dedicated replica entries.
    Returns True iff the line was printed."""
    if not force and not os.environ.get("VELES_TPU_REPLICA_ANNOUNCE"):
        return False
    print("%s port=%d pid=%d" % (READY_LINE, api.port, os.getpid()),
          file=stream if stream is not None else sys.stdout,
          flush=True)
    return True


def parse_ready_line(line):
    """``{"port": int, "pid": int|None}`` for a READY handshake line,
    or None when the line is not one (startup chatter is expected —
    callers scan until the first match)."""
    if not line or not line.lstrip().startswith(READY_LINE):
        return None
    out = {"port": None, "pid": None}
    for tok in line.split():
        for key in ("port", "pid"):
            if tok.startswith(key + "="):
                try:
                    out[key] = int(tok.split("=", 1)[1])
                except ValueError:
                    pass
    return out if out["port"] is not None else None


def install_sigterm_drain(api, exit_code=0, grace_s=None,
                          on_drained=None):
    """SIGTERM → graceful drain for a standalone serve process: stop
    admission, finish in-flight, exit ``exit_code`` — the same
    lifecycle a fleet replica walks, instead of the bare PR 5
    crashdump-and-die.  ``grace_s`` caps the wait (default: the
    ``drain_timeout_ms`` knob plus slack).  Must run on the main
    thread (signal API); returns the previous handler.

    The handler itself only *starts* the drain (signal context must
    stay tiny); a waiter thread watches for drained, runs
    ``on_drained`` (e.g. a flight dump — atexit hooks do NOT survive
    the ``os._exit``), stops the endpoint, and ``os._exit``\\ s so the
    exit status is 0 no matter what non-daemon machinery the
    embedding process runs."""
    import signal

    from veles_tpu.config import root
    if grace_s is None:
        grace_s = float(root.common.serve.get(
            "drain_timeout_ms", 30000)) / 1e3 + 5.0

    def _waiter():
        api.wait_drained(timeout=grace_s)
        if on_drained is not None:
            try:
                on_drained()
            except Exception:   # noqa: BLE001 — exiting anyway
                pass
        try:
            api.stop()
        except Exception:   # noqa: BLE001 — exiting anyway
            pass
        os._exit(exit_code)

    def on_sigterm(signum, frame):
        flight.record("serve.sigterm_drain", pid=os.getpid())
        api.drain(reason="SIGTERM")
        threading.Thread(target=_waiter, name="VelesSigtermDrain",
                         daemon=True).start()

    return signal.signal(signal.SIGTERM, on_sigterm)
