"""Replica fleet tier — health-routed multi-engine serving (ref: the
Veles master–slave triad, veles/server.py + veles/client.py: slaves die
and respawn without losing the job; here the *serving* half, rebuilt
for TPU engine replicas over plain HTTP).

Topology::

    clients ──HTTP──▶ FleetRouter ──HTTP──▶ replica RESTfulAPI #0
                         │   ▲                 (ContinuousEngine)
                         │   └── health probe  replica RESTfulAPI #1
                         └────────────────────▶        ...

The router owns a registry of N engine replicas — spawned in-process
(:meth:`FleetRouter.spawn_local`, tests and single-host fleets) or
registered by URL (:meth:`FleetRouter.register` / POST ``/register``,
separate processes or hosts).  A health thread probes each replica's
``{path}/health`` surface (PR 6 ``lifecycle_status()`` + the drain
state) every ``root.common.serve.fleet.health_interval_ms``; the
request path additionally marks a replica down the moment a connect or
read fails, so failover usually beats the probe.

Routing contract (docs/services.md "Fleet serving"):

* **session affinity** — a request carrying ``{"session": key}`` pins
  to one replica (``fleet.affinity='session'``) so that replica's
  prefix cache keeps hitting; the pin moves (and a
  ``serve.failover`` flight event records it) only when the replica
  leaves the pool.
* **retry with backoff + jitter** — a dead replica's requests retry
  onto a survivor up to ``fleet.retry_max`` times, sleeping
  ``backoff_base_ms * 2^attempt`` (capped at ``backoff_max_ms``,
  jittered to [0.5, 1.0)x) between attempts.
* **shed routing** — a replica's 503 (SLO shed valve open, or
  draining) makes the router try the next replica immediately; only
  when every live replica sheds does the client see a 503, carrying
  the largest Retry-After any replica offered.
* **mid-stream failover** — a replica dying mid-NDJSON-stream is
  invisible to the client: the router resubmits the prompt plus the
  already-delivered tokens as a prefix-resume continuation on a
  survivor and splices the streams at the recorded offset, so the
  client sees ONE uninterrupted stream whose concatenation is exactly
  the uninterrupted result (greedy decode is deterministic across
  replicas of the same model).
* **graceful drain** — ``/drain`` (or SIGTERM on the replica, see
  ``restful.install_sigterm_drain``) flips a replica to draining: the
  router stops routing to it, its in-flight requests finish, and the
  health loop deregisters it once drained.

Fleet churn is observable: ``serve.replica_up`` / ``serve.replica_down``
/ ``serve.failover`` / ``serve.drain`` flight events land in the same
``veles-tpu-blackbox`` timeline as everything else."""

import collections
import http.client
import json
import math
import random
import threading
import time
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlsplit

from veles_tpu.logger import Logger
from veles_tpu.telemetry import flight, tracing


class NoReplicaError(RuntimeError):
    """No live replica could take the request (all down, draining, or
    shedding past the retry budget)."""

    def __init__(self, message, retry_after_s=1.0):
        super(NoReplicaError, self).__init__(message)
        self.retry_after_s = float(retry_after_s)


class Replica(object):
    """One registry entry.  State machine: ``up`` ⇄ ``down``,
    ``up → draining → (deregistered)``; transitions happen on the
    health thread or (down only) the request path.

    ``role``: None (any work) / ``"prefill"`` / ``"decode"`` — the
    disaggregated-prefill fleet roles (docs/services.md): long
    prompts' admission prefill routes to prefill-role replicas first.
    ``pending_cost_ms``: predicted device residency (ms) of the
    requests this router currently has in flight on the replica — the
    cost-weighted placement signal.  Mutated ONLY through
    FleetRouter._charge (under the router lock): += / -= from
    concurrent handler threads would lose updates and drift the gauge
    permanently, and min-cost placement would then favor the drifted
    replica forever."""

    UP, DRAINING, DOWN = "up", "draining", "down"
    ROLES = (None, "prefill", "decode")

    __slots__ = ("rid", "url", "host", "port", "path", "state",
                 "fails", "last_health", "api", "role",
                 "pending_cost_ms")

    def __init__(self, rid, url, api=None, role=None):
        parts = urlsplit(url)
        self.rid = rid
        self.url = url
        self.host = parts.hostname
        self.port = parts.port
        self.path = parts.path.rstrip("/") or "/service"
        self.state = Replica.UP
        self.fails = 0            # consecutive health-probe failures
        self.last_health = None
        self.api = api            # in-process RESTfulAPI (spawn_local)
        if role not in Replica.ROLES:
            raise ValueError("role must be one of %s, got %r"
                             % (Replica.ROLES, role))
        self.role = role
        self.pending_cost_ms = 0.0

    def describe(self):
        return {"url": self.url, "state": self.state,
                "fails": self.fails, "role": self.role,
                "pending_cost_ms": round(self.pending_cost_ms, 3),
                "health": self.last_health}


class FleetRouter(Logger):
    """Front-end HTTP tier over N engine replicas: health-checked
    registry, session-affine routing, retry/backoff failover,
    shed propagation, mid-stream prefix-resume splicing, and drain
    orchestration.  Endpoints (all under ``path``, default
    ``/fleet``)::

        POST {path}             route one serving request (buffered or
                                NDJSON streaming, same body contract
                                as the replica RESTfulAPI — plus an
                                optional top-level "session" key)
        GET  {path}/metrics     router counters + per-replica states
        GET  {path}/health      fleet health (503 iff no live replica)
        POST {path}/register    {"url": "http://host:port/service"}
        POST {path}/deregister  {"replica": rid} | {"url": ...}
        POST {path}/drain       {"replica": rid} — graceful drain
    """

    def __init__(self, host="127.0.0.1", port=0, path="/fleet",
                 health_interval_ms=None, retry_max=None,
                 backoff_base_ms=None, backoff_max_ms=None,
                 affinity=None, stream_read_timeout_ms=None,
                 rng_seed=None, placement=None,
                 prefill_prompt_min=None, prefill_handoff_new=None):
        super(FleetRouter, self).__init__()
        from veles_tpu.config import root
        from veles_tpu.services.costing import RequestCost
        cfg = root.common.serve.fleet

        def knob(arg, name, default):
            return arg if arg is not None else cfg.get(name, default)

        self.host, self.port, self.path = host, port, path
        self.health_interval_s = float(
            knob(health_interval_ms, "health_interval_ms", 100)) / 1e3
        self.retry_max = int(knob(retry_max, "retry_max", 3))
        self.backoff_base_s = float(
            knob(backoff_base_ms, "backoff_base_ms", 20)) / 1e3
        self.backoff_max_s = float(
            knob(backoff_max_ms, "backoff_max_ms", 2000)) / 1e3
        self.affinity = str(knob(affinity, "affinity", "session"))
        #: "cost": price each request (prompt_len x prefill cost +
        #: max_new x measured decode ms/tok) and route to the replica
        #: with the least predicted outstanding work; "round_robin":
        #: the PR 7 rotation.  Session affinity wins over either.
        self.placement = str(knob(placement, "placement", "cost"))
        if self.placement not in ("cost", "round_robin"):
            raise ValueError("fleet.placement must be 'cost' or "
                             "'round_robin', got %r" % self.placement)
        #: disaggregated-prefill routing: prompts at least this long
        #: go to a prefill-role replica first (0 disables); the
        #: prefill replica decodes the first prefill_handoff_new
        #: tokens, then the stream continues on a decode replica via
        #: the prefix-resume splice (PR 7 failover machinery)
        self.prefill_prompt_min = int(
            knob(prefill_prompt_min, "prefill_prompt_min", 64))
        self.prefill_handoff_new = max(1, int(
            knob(prefill_handoff_new, "prefill_handoff_new", 4)))
        #: the calibrated request pricer (services.costing): seeded
        #: from tools/cost_model device constants, calibrated against
        #: the fleet's measured ms/tok off the health probes
        self.cost = RequestCost()
        self.read_timeout_s = float(
            knob(stream_read_timeout_ms, "stream_read_timeout_ms",
                 30000)) / 1e3
        #: buffered requests yield no bytes until the decode is done —
        #: their whole-request budget must scale with a real decode,
        #: not with the per-chunk stream timeout
        self.request_timeout_s = float(
            cfg.get("request_timeout_ms", 300000)) / 1e3
        self._lock = threading.Lock()
        self._replicas = {}              # rid -> Replica
        self._next_rid = 0
        self._sessions = {}              # session key -> rid
        self._rr = 0                     # round-robin cursor
        self._rng = random.Random(rng_seed)
        self._counters = {
            "routed": 0,            # requests that got a 2xx/4xx answer
            "retries": 0,           # extra attempts after a failure
            "failovers": 0,         # requests rerouted off a dead replica
            "resumed_streams": 0,   # mid-stream prefix-resume splices
            "shed_rejects": 0,      # 503s the router itself returned
            "session_moves": 0,     # affinity pins that had to move
            "prefill_handoffs": 0,  # prefill-replica -> decode splices
        }
        self._local_apis = []            # spawn_local ownership
        self._closed = False
        self._server = None
        self._thread = None
        self._health_wake = threading.Event()
        self._health_thread = None
        self._next_probe = {}            # rid -> monotonic next-due ts
        #: the fleet-manager block (ServeFleetMaster.note_fleet):
        #: desired count, scale/replace totals — surfaced on /metrics
        #: and /health next to the live registry
        self._fleet = None
        self._gauges = None
        #: fleet-wide per-phase latency rollup (docs/services.md
        #: "Request tracing"): per-(replica, phase) windows feeding
        #: metrics()["phases"] p50/p99, plus the registry histogram
        #: veles_fleet_phase_ms{phase, replica} (lazy, fail-soft)
        self._phase_stats = {}           # (rid, phase) -> deque of ms
        self._phase_hist = None

    # ----------------------------------------------------------- registry
    def register(self, url, api=None, role=None):
        """Add a replica by URL (its RESTfulAPI work path, e.g.
        ``http://127.0.0.1:8180/service``).  Optimistically up — the
        first health probe (≤ one interval away) corrects it.
        ``role``: None / "prefill" / "decode" (disaggregated-prefill
        routing); re-registration may update it (a replaced replica's
        successor can carry a different role).  Returns the replica
        id."""
        if role not in Replica.ROLES:
            # validate up front so a typo'd role is LOUD on both the
            # fresh and the re-registration path (silently keeping
            # the old role would misroute long prompts forever)
            raise ValueError("role must be one of %s, got %r"
                             % (Replica.ROLES, role))
        rep = None
        fresh = False
        with self._lock:
            for existing in self._replicas.values():
                if existing.url == url:
                    rep = existing
                    break
            if rep is None:
                fresh = True
                rep = Replica(self._next_rid, url, api=api, role=role)
                self._next_rid += 1
                self._replicas[rep.rid] = rep
            elif role is not None:
                rep.role = role
        if fresh:
            flight.record("serve.replica_up", replica=rep.rid,
                          url=url, registered=True, role=role)
            self.info("replica %d registered: %s%s", rep.rid, url,
                      " (role=%s)" % role if role else "")
            self._export_fleet_gauges()
        else:
            # re-registration (e.g. a restarted replica announcing
            # itself): bring a down entry back into rotation — with
            # its own replica_up event — instead of logging a
            # spurious one while the state stays down
            self._mark_up(rep)
        self._health_wake.set()
        return rep.rid

    def deregister(self, rid=None, url=None, reason="deregister"):
        """Drop a replica from the registry (its pinned sessions re-pin
        on their next request).  True iff something was removed."""
        with self._lock:
            if rid is None and url is not None:
                for r in self._replicas.values():
                    if r.url == url:
                        rid = r.rid
                        break
            rep = self._replicas.pop(rid, None)
            self._next_probe.pop(rid, None)
            if rep is not None:
                for key in [k for k, v in self._sessions.items()
                            if v == rid]:
                    del self._sessions[key]
        if rep is None:
            return False
        flight.record("serve.replica_down", replica=rep.rid,
                      url=rep.url, reason=reason)
        self.info("replica %d deregistered (%s)", rep.rid, reason)
        self._export_fleet_gauges()
        return True

    def spawn_local(self, generator, n, input_shape=None, roles=None,
                    **engine_kw):
        """Spawn ``n`` in-process replicas around one (read-only)
        generator — each gets its own RESTfulAPI + ContinuousEngine on
        a loopback port, registered here and owned by :meth:`stop`.
        The single-host fleet: engine state is per-replica, weights
        are shared.  ``roles``: optional per-replica role list
        (None / "prefill" / "decode").  Returns the replica ids."""
        from veles_tpu.services.restful import RESTfulAPI
        if input_shape is None:
            input_shape = (generator.max_len,)
        rids = []
        for i in range(n):
            api = RESTfulAPI(lambda x: x, input_shape, port=0,
                             generator=generator, **engine_kw)
            api.start()
            self._local_apis.append(api)
            rids.append(self.register(
                "http://127.0.0.1:%d%s" % (api.port, api.path),
                api=api, role=roles[i] if roles else None))
        return rids

    def replicas(self):
        """Snapshot of the registry for metrics/health surfaces."""
        with self._lock:
            return {rid: rep.describe()
                    for rid, rep in sorted(self._replicas.items())}

    # ------------------------------------------------------------- health
    def _probe(self, rep):
        """One GET {path}/health against a replica.  Returns the
        payload dict or raises."""
        conn = http.client.HTTPConnection(
            rep.host, rep.port,
            timeout=max(self.health_interval_s * 2, 1.0))
        try:
            conn.request("GET", rep.path + "/health")
            resp = conn.getresponse()
            return json.loads(resp.read() or b"{}")
        finally:
            conn.close()

    #: golden-ratio fraction — consecutive rids land maximally spread
    #: over the interval (low-discrepancy), and the offset never
    #: depends on registration order or wall time (deterministic)
    _PHASE_GOLDEN = 0.6180339887498949

    @classmethod
    def probe_phase(cls, rid, interval_s):
        """Deterministic per-replica phase offset in ``(0,
        interval_s)``: replica ``rid``'s health probes fire at
        ``register + phase + k*interval`` instead of every replica
        being probed in lockstep — at large N a synchronized probe
        round is a thundering herd against the very replicas the
        probes are supposed to protect.  Golden-ratio spacing keeps
        any two rids' phases far apart, and the ``rid + 1`` shift
        keeps every phase strictly positive — no replica's first
        probe races its own registration (test-pinned in
        tests/test_fleet.py)."""
        return float(interval_s) * (((rid + 1) * cls._PHASE_GOLDEN)
                                    % 1.0)

    def _health_loop(self):
        # fine-grained scheduler tick: each replica keeps its OWN
        # probe period (one probe per health interval, phase-offset by
        # probe_phase), so detection latency stays <= one interval
        # while N replicas are never probed in lockstep
        tick = max(self.health_interval_s / 8.0, 0.002)
        while not self._closed:
            self._health_wake.wait(tick)
            self._health_wake.clear()
            if self._closed:
                return
            now = time.monotonic()
            due = []
            with self._lock:
                for rep in self._replicas.values():
                    nxt = self._next_probe.get(rep.rid)
                    if nxt is None:
                        # first probe lands within one phase (< one
                        # interval) of registration
                        nxt = now + self.probe_phase(
                            rep.rid, self.health_interval_s)
                        self._next_probe[rep.rid] = nxt
                    if now >= nxt:
                        self._next_probe[rep.rid] = \
                            now + self.health_interval_s
                        due.append(rep)
            if not due:
                continue
            # probe CONCURRENTLY: each probe is bounded by its socket
            # timeout, so one black-holed replica delays this round by
            # its own timeout at most — never head-of-line-blocking
            # detection of the replicas behind it
            threads = [threading.Thread(target=self._probe_one,
                                        args=(rep,), daemon=True)
                       for rep in due]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

    def _probe_one(self, rep):
        try:
            payload = self._probe(rep)
        except Exception as e:  # noqa: BLE001 — probe failure
            rep.fails += 1
            # a DRAINING replica going unreachable has finished
            # (or died) — either way it leaves the pool
            if rep.state == Replica.DRAINING:
                self.deregister(rep.rid,
                                reason="drained (unreachable)")
            else:
                self._mark_down(rep, "health probe failed: %r"
                                % (e,))
            return
        rep.fails = 0
        rep.last_health = payload
        # cost-model calibration: every health probe carries the
        # replica's measured decode p50 ms/tok (and, with segmented
        # prefill on, its measured prefill rate) — the predicted
        # request costs track the fleet's live reality
        try:
            m = float(payload.get("p50_ms_per_tok") or 0.0)
            mp = float(payload.get("prefill_ms_per_tok") or 0.0)
            if m > 0:
                self.cost.calibrate(m, mp if mp > 0 else None)
        except (TypeError, ValueError):
            pass
        state = payload.get("state", "serving")
        if state == "serving":
            self._mark_up(rep)
        elif state == "draining":
            self._mark_draining(rep, "replica reported draining")
        elif state == "drained":
            # a fast drain can skip the "draining" probe window
            # entirely — still record the drain before the exit
            self._mark_draining(rep, "replica reported drained")
            self.deregister(rep.rid, reason="drained")
        else:
            # "failed" (dead engine behind a live HTTP shell) or
            # anything unrecognized: not routable
            self._mark_down(rep, "replica reported state %r"
                            % (state,))

    def _mark_down(self, rep, reason):
        with self._lock:
            if rep.rid not in self._replicas \
                    or rep.state == Replica.DOWN:
                return
            rep.state = Replica.DOWN
        flight.record("serve.replica_down", replica=rep.rid,
                      url=rep.url, reason=str(reason)[:200])
        self.warning("replica %d DOWN: %s", rep.rid, reason)
        self._export_fleet_gauges()

    def _mark_up(self, rep):
        with self._lock:
            if rep.rid not in self._replicas \
                    or rep.state == Replica.UP:
                return
            prev, rep.state = rep.state, Replica.UP
        flight.record("serve.replica_up", replica=rep.rid,
                      url=rep.url, was=prev)
        self.info("replica %d UP (was %s)", rep.rid, prev)
        self._export_fleet_gauges()

    def _mark_draining(self, rep, reason):
        with self._lock:
            if rep.rid not in self._replicas \
                    or rep.state == Replica.DRAINING:
                return
            rep.state = Replica.DRAINING
        flight.record("serve.drain", replica=rep.rid, url=rep.url,
                      reason=str(reason))
        self.info("replica %d draining: %s", rep.rid, reason)
        self._export_fleet_gauges()

    def drain_replica(self, rid):
        """Admin drain: tell the replica to stop admitting and finish
        in-flight (POST its ``/drain``), mark it draining here so no
        new request routes to it; the health loop deregisters it once
        it reports drained.  True iff the replica was known."""
        with self._lock:
            rep = self._replicas.get(rid)
        if rep is None:
            return False
        self._mark_draining(rep, "admin drain")
        try:
            conn = http.client.HTTPConnection(rep.host, rep.port,
                                              timeout=5.0)
            try:
                conn.request("POST", rep.path + "/drain", b"{}",
                             {"Content-Type": "application/json"})
                conn.getresponse().read()
            finally:
                conn.close()
        except Exception as e:  # noqa: BLE001 — it may already be dead
            self._mark_down(rep, "drain POST failed: %r" % (e,))
        return True

    # ------------------------------------------------------------ routing
    def backoff_delay(self, attempt):
        """Failover backoff before retry ``attempt`` (0-based):
        ``backoff_base * 2^attempt`` capped at ``backoff_max``, then
        jittered to [0.5, 1.0)x so a burst of failovers does not
        stampede the survivor in lockstep."""
        d = min(self.backoff_max_s,
                self.backoff_base_s * (2 ** attempt))
        return d * (0.5 + 0.5 * self._rng.random())

    def _charge(self, rep, delta_ms):
        """Adjust a replica's outstanding predicted work — the ONLY
        writer of ``pending_cost_ms`` (handler threads race; an
        unlocked += would lose updates and drift the gauge
        permanently)."""
        with self._lock:
            rep.pending_cost_ms += delta_ms

    def _backlog_ms(self, rep):
        """Predicted outstanding work on a replica: what THIS router
        has in flight there (pending_cost_ms) plus the prefill
        backlog its last health probe reported (work routed around
        us, or queued before a restart), priced by the calibrated
        prefill cost."""
        out = rep.pending_cost_ms
        h = rep.last_health or {}
        try:
            out += (float(h.get("queued_prefill_tokens") or 0)
                    * self.cost.prefill_ms_per_tok)
        except (TypeError, ValueError):
            pass
        return out

    def _pick(self, session=None, exclude=(), role=None):
        """Choose a live replica: the session's pinned one when
        affinity is on and it is still up, else cost-weighted (least
        predicted outstanding work — ``placement='cost'``) or
        round-robin.  ``role='prefill'`` prefers prefill-role
        replicas; any other pick prefers NON-prefill ones (the
        prefill tier must stay clear for the next long prompt) —
        either falls back to the whole up set when its preferred tier
        is empty, so roles can never strand a request.  Returns None
        when no up replica remains outside ``exclude``."""
        with self._lock:
            ups = [r for r in self._replicas.values()
                   if r.state == Replica.UP and r.rid not in exclude]
            if not ups:
                return None
            if role == "prefill":
                tier = [r for r in ups if r.role == "prefill"]
            else:
                tier = [r for r in ups if r.role != "prefill"]
            ups = tier or ups
            ups.sort(key=lambda r: r.rid)
            if session is not None and self.affinity == "session":
                pinned = self._sessions.get(session)
                for r in ups:
                    if r.rid == pinned:
                        return r
                pick = self._placement_pick(ups, session)
                pin_rep = self._replicas.get(pinned) \
                    if pinned is not None else None
                if pin_rep is not None \
                        and pin_rep.state == Replica.UP:
                    # the pinned replica is alive but excluded for
                    # THIS request only (shed 503 / already tried /
                    # wrong role tier): route around WITHOUT moving
                    # the pin — a transient valve blip must not cost
                    # the session its prefix cache
                    return pick
                # pin (first sight) or re-pin (pinned replica left
                # the pool): stable hash so a cold router maps the
                # same sessions to the same replicas
                if pinned is not None and pinned != pick.rid:
                    self._counters["session_moves"] += 1
                self._sessions[session] = pick.rid
                return pick
            return self._placement_pick(ups, None)

    def _placement_pick(self, ups, session):
        """Placement policy over an already-filtered up set (lock
        held).  Sessions keep the stable crc32 hash — affinity is
        about prefix-cache reuse, and a cold router must map the same
        sessions to the same replicas regardless of load."""
        if session is not None:
            return ups[zlib.crc32(str(session).encode()) % len(ups)]
        if self.placement == "cost":
            costs = [(self._backlog_ms(r), r) for r in ups]
            best = min(c for c, _ in costs)
            # ties (an idle fleet prices every replica 0) rotate —
            # cost must degrade to round-robin, never hammer the
            # lowest rid with every small request
            cands = [r for c, r in costs if c <= best + 1e-9]
            r = cands[self._rr % len(cands)]
            self._rr += 1
            return r
        r = ups[self._rr % len(ups)]
        self._rr += 1
        return r

    # --------------------------------------------- pricing & roles
    @staticmethod
    def _gen_opts(parsed):
        opts = (parsed or {}).get("generate") \
            if isinstance(parsed, dict) else None
        return opts if isinstance(opts, dict) else None

    @staticmethod
    def _prompt_rows(parsed):
        """The request's prompt rows as a list of lists (or None for
        non-generate / malformed bodies — priced nominally)."""
        row = (parsed or {}).get("input") \
            if isinstance(parsed, dict) else None
        if not isinstance(row, list) or not row:
            return None
        if isinstance(row[0], list):
            return row
        return [row]

    def _price(self, parsed):
        """Predicted device residency (ms) of one request — the
        cost-weighted placement weight.  Non-generate forwards price
        one decode token (nominal: they are single forward passes)."""
        opts = self._gen_opts(parsed)
        rows = self._prompt_rows(parsed)
        if opts is None or rows is None:
            return self.cost.decode_ms_per_tok
        max_new = int(opts.get("max_new", 16))
        return sum(self.cost.price(len(r), max_new) for r in rows)

    def _handoff_plan(self, parsed):
        """Disaggregated-prefill verdict for one request: ``(role,
        cap)``.  ``role`` is "prefill" when the prompt is long enough
        to route to the prefill tier (None otherwise); ``cap`` > 0
        means two-phase — the prefill replica serves the admission
        prefill plus the first ``cap`` tokens, then the stream
        continues on a decode replica via the prefix-resume splice.
        cap == 0 with role "prefill" = the whole (short-decode)
        request runs on the prefill replica."""
        if self.prefill_prompt_min <= 0:
            return None, 0
        if not isinstance(parsed, dict) or parsed.get("resume"):
            # a resume continuation is already-admitted work being
            # relocated (failover, or OUR OWN decode leg) — it must
            # never re-enter the handoff plan, or a long resumed
            # prompt would ping-pong between the tiers forever
            return None, 0
        opts = self._gen_opts(parsed)
        rows = self._prompt_rows(parsed)
        if opts is None or rows is None or len(rows) != 1:
            return None, 0
        if len(rows[0]) < self.prefill_prompt_min:
            return None, 0
        with self._lock:
            has_prefill = any(r.state == Replica.UP
                              and r.role == "prefill"
                              for r in self._replicas.values())
        if not has_prefill:
            return None, 0
        max_new = int(opts.get("max_new", 16))
        cap = min(self.prefill_handoff_new, max_new)
        return "prefill", (cap if cap < max_new else 0)

    @staticmethod
    def _capped_body(parsed, cap, resume=False):
        """The prefill-leg request: same prompt, max_new capped to the
        handoff budget (the decode leg resumes from there)."""
        body = dict(parsed)
        opts = dict(body["generate"])
        opts["max_new"] = int(cap)
        body["generate"] = opts
        if resume:
            body["resume"] = True
        return json.dumps(body).encode()

    @staticmethod
    def _retry_after_of(headers, body):
        try:
            ra = headers.get("Retry-After")
            if ra is not None:
                return float(ra)
            return float(json.loads(body).get("retry_after_s", 1.0))
        except (TypeError, ValueError):
            return 1.0

    def _request_headers(self, trace, tspan, rep):
        """The replica-hop headers: content type plus the trace
        context — each attempt gets its own ``router.leg`` span (the
        replica's spans parent onto it), so failover attempts stay
        distinguishable in the reconstructed timeline."""
        headers = {"Content-Type": "application/json"}
        if trace:
            leg = tracing.span_add(trace, "router.leg", parent=tspan,
                                   replica=rep.rid)
            headers[tracing.TRACE_HEADER] = tracing.format_header(
                trace, leg)
        return headers

    def _forward_buffered(self, rep, body, trace=None, tspan=None):
        conn = http.client.HTTPConnection(rep.host, rep.port,
                                          timeout=self.request_timeout_s)
        try:
            conn.request("POST", rep.path, body,
                         self._request_headers(trace, tspan, rep))
            resp = conn.getresponse()
            return resp.status, dict(resp.getheaders()), resp.read()
        finally:
            conn.close()

    def _note_phases(self, rep, phases, total_ms=None, trace=None,
                     tspan=None):
        """Fleet rollup of one completed leg's phase decomposition
        (the replica reported queue/prefill/decode on its terminal
        payload).  ``total_ms`` (router-observed wall latency) turns
        the remainder into the ``stream`` phase — delivery + network
        overhead the replica cannot see — keeping the four phases a
        non-overlapping partition of what the router measured."""
        phases = dict(phases) if isinstance(phases, dict) else {}
        if not phases:
            return
        if total_ms is not None:
            known = sum(float(phases.get(p, 0.0))
                        for p in ("queue", "prefill", "decode"))
            phases["stream"] = round(max(0.0, total_ms - known), 3)
            if trace:
                tracing.span_add(trace, "phase.stream", parent=tspan,
                                 dur_ms=phases["stream"],
                                 replica=rep.rid)
        try:
            from veles_tpu import telemetry
            if self._phase_hist is None:
                self._phase_hist = telemetry.registry.histogram(
                    "veles_fleet_phase_ms",
                    "fleet-wide per-phase request latency by serving "
                    "replica",
                    labelnames=("phase", "replica"),
                    buckets=tracing.PHASE_BUCKETS_MS)
            for phase, ms in phases.items():
                self._phase_hist.observe(float(ms), phase=phase,
                                         replica=str(rep.rid))
        except Exception:   # noqa: BLE001 — fail-soft telemetry
            pass
        with self._lock:
            for phase, ms in phases.items():
                key = (str(rep.rid), phase)
                window = self._phase_stats.get(key)
                if window is None:
                    window = self._phase_stats[key] = \
                        collections.deque(maxlen=512)
                try:
                    window.append(float(ms))
                except (TypeError, ValueError):
                    pass

    def route_buffered(self, body, session=None, parsed=None,
                       trace=None, tspan=None):
        """Route one non-streaming request; returns (status, payload
        bytes, extra headers).  Long prompts route to the prefill
        tier — two-phase when the decode residency exceeds the
        handoff budget (prefill leg capped, decode continuation via
        the resume body on a decode replica; the second leg's result
        is already the full concatenation).  Raises
        :class:`NoReplicaError` when the retry budget is exhausted
        (the HTTP layer maps it to 503 + Retry-After)."""
        if parsed is None:
            try:
                parsed = json.loads(body)
            except ValueError:
                parsed = None
        t0 = time.monotonic()
        role, cap = self._handoff_plan(parsed)
        if role is not None and cap:
            out = self._route_buffered_handoff(parsed, session, cap,
                                               trace=trace,
                                               tspan=tspan)
            if out is not None:
                return out
            # the two-phase path could not run (prefill tier emptied,
            # torn first leg): fall through single-phase — the
            # request must never be lost to an optimization
        cost = self._price(parsed)
        tried = set()
        shed_ra = None
        last_err = None
        attempt = 0
        while attempt <= self.retry_max:
            rep = self._pick(session=session, exclude=tried,
                             role=role)
            if rep is None:
                break
            self._charge(rep, cost)
            try:
                try:
                    status, headers, payload = self._forward_buffered(
                        rep, body, trace=trace, tspan=tspan)
                except (OSError, http.client.HTTPException) as e:
                    last_err = e
                    tried.add(rep.rid)
                    self._mark_down(rep, "request failed: %r" % (e,))
                    self._note_failover(rep, session, attempt,
                                        stream=False, trace=trace,
                                        tspan=tspan)
                    with self._lock:
                        self._counters["retries"] += 1
                    attempt += 1
                    time.sleep(self.backoff_delay(attempt - 1))
                    continue
                if status == 503:
                    # shed valve open or draining: route around it —
                    # immediately, the next replica may be idle.  NOT
                    # an attempt: the retry budget is for failures, so
                    # a wide fleet with several shedding members still
                    # gets every live replica tried once
                    shed_ra = max(shed_ra or 0.0,
                                  self._retry_after_of(headers,
                                                       payload))
                    tried.add(rep.rid)
                    continue
                with self._lock:
                    self._counters["routed"] += 1
                if status == 200:
                    try:
                        self._note_phases(
                            rep, json.loads(payload).get("phases"),
                            total_ms=(time.monotonic() - t0) * 1e3,
                            trace=trace, tspan=tspan)
                    except ValueError:
                        pass
                return status, payload, ()
            finally:
                self._charge(rep, -cost)
        with self._lock:
            self._counters["shed_rejects"] += 1
        ra = shed_ra if shed_ra is not None else 1.0
        raise NoReplicaError(
            "no replica could take the request (tried %d, last error "
            "%r)%s" % (len(tried), last_err,
                       "; every live replica is shedding"
                       if shed_ra is not None else ""),
            retry_after_s=ra)

    def _route_buffered_handoff(self, parsed, session, cap,
                                trace=None, tspan=None):
        """Two-phase buffered request: prefill leg (capped max_new)
        on the prefill tier, then the decode continuation — the same
        prefix-resume body the failover path uses — on a decode
        replica.  Returns (status, payload, headers), a deterministic
        replica verdict, or None to fall back single-phase."""
        rows = self._prompt_rows(parsed)
        body1 = self._capped_body(parsed, cap)
        cost1 = self.cost.price(len(rows[0]), cap)
        tried = set()
        for _ in range(self.retry_max + 1):
            rep = self._pick(session=session, exclude=tried,
                             role="prefill")
            if rep is None:
                return None
            self._charge(rep, cost1)
            try:
                status, headers, payload = self._forward_buffered(
                    rep, body1, trace=trace, tspan=tspan)
            except (OSError, http.client.HTTPException) as e:
                tried.add(rep.rid)
                self._mark_down(rep, "request failed: %r" % (e,))
                self._note_failover(rep, session, 0, stream=False,
                                    trace=trace, tspan=tspan)
                with self._lock:
                    self._counters["retries"] += 1
                continue
            finally:
                self._charge(rep, -cost1)
            if status == 503:
                tried.add(rep.rid)
                continue
            if status != 200:
                # deterministic verdict (validation 400 / deadline
                # 504): every replica would repeat it
                return status, payload, ()
            try:
                decoded = json.loads(payload)
                first = decoded["result"][0]
            except (ValueError, KeyError, IndexError, TypeError):
                return None
            delivered = [int(t) for t in first[len(rows[0]):]]
            with self._lock:
                self._counters["prefill_handoffs"] += 1
            flight.record("serve.prefill_handoff", replica=rep.rid,
                          session=session, prompt_len=len(rows[0]),
                          handoff=len(delivered), stream=False,
                          trace=trace)
            if trace:
                tracing.span_add(trace, "router.handoff",
                                 parent=tspan, replica=rep.rid,
                                 handoff=len(delivered))
            # the prefill leg's phase share rolls up under the
            # PREFILL replica; the decode continuation reports its
            # own under the survivor
            self._note_phases(rep, decoded.get("phases"))
            resume = self._resume_body(parsed, delivered)
            return self.route_buffered(resume, session=session,
                                       trace=trace, tspan=tspan)
        return None

    def _note_failover(self, rep, session, attempt, stream,
                       delivered=0, trace=None, tspan=None):
        with self._lock:
            self._counters["failovers"] += 1
        flight.record("serve.failover", replica=rep.rid,
                      session=session, attempt=attempt,
                      stream=bool(stream), delivered=int(delivered),
                      trace=trace)
        if trace:
            tracing.span_add(trace, "router.failover", parent=tspan,
                             replica=rep.rid, attempt=attempt,
                             delivered=int(delivered))

    # ---------------------------------------------------------- streaming
    @staticmethod
    def _resume_body(parsed, delivered, cap=0):
        """The prefix-resume continuation request: prompt grows by the
        already-delivered tokens, max_new shrinks by them — the
        survivor decodes exactly the missing suffix (deterministic for
        greedy decode, and for sampled rows too: the per-row key
        stream is (seed, absolute position), which the longer prompt
        preserves).  ``cap`` > 0 bounds the continuation at the
        prefill-handoff budget instead of the request's full max_new
        (a failover WITHIN the prefill leg must not decode the whole
        request on the prefill tier)."""
        opts = dict(parsed["generate"])
        row = parsed["input"]
        if row and isinstance(row[0], list):
            row = row[0]
        total = int(cap) if cap else int(opts.get("max_new", 16))
        opts["max_new"] = total - len(delivered)
        body = dict(parsed)
        body["input"] = list(row) + list(delivered)
        body["generate"] = opts
        # already-admitted work being relocated: the survivor must not
        # shed it (the client's 200 is committed — a 503 here would
        # turn the failover into a lost request)
        body["resume"] = True
        return json.dumps(body).encode()

    def route_stream(self, parsed, body, session, send_headers,
                     write_line, trace=None, tspan=None):
        """Route one NDJSON streaming request, splicing across replica
        deaths.  ``send_headers()`` commits the client's 200 exactly
        once; ``write_line(bytes)`` forwards one NDJSON line (raising
        on a dead client aborts upstream too).  Raises
        :class:`NoReplicaError` only BEFORE headers are committed;
        after that, terminal failures surface as an ``{"error": ...}``
        NDJSON line (the streaming contract — the status code is
        gone).

        Disaggregated prefill rides the SAME loop: a long prompt's
        first leg goes to a prefill-role replica with max_new capped
        at the handoff budget; its (swallowed) done line flips the
        loop into the decode phase, where the continuation is exactly
        the failover machinery's prefix-resume body — one
        byte-identical client stream either way, and a prefill
        replica dying MID-prefill is just a failover."""
        max_new = int(parsed["generate"].get("max_new", 16))
        t0 = time.monotonic()
        plan_role, cap = self._handoff_plan(parsed)
        cost = self._price(parsed)
        delivered = []            # new tokens already sent to client
        committed = False
        # two exclusion tiers: a DEAD replica stays excluded for the
        # request's lifetime, but a SHED 503 is transient — after a
        # failover the resume is shed-exempt (already-admitted work),
        # so previously-shedding replicas become eligible again
        tried_dead = set()
        tried_shed = set()
        attempts = []             # (rid, outcome) per attempt
        shed_ra = None
        attempt = 0
        while attempt <= self.retry_max:
            # handoff phase: still inside the prefill leg?
            in_handoff = bool(cap) and len(delivered) < cap
            role = None
            if plan_role is not None:
                role = "prefill" if (in_handoff or not cap) \
                    else "decode"
            rep = self._pick(session=session,
                             exclude=tried_dead | tried_shed,
                             role=role)
            if rep is None:
                break
            if delivered:
                send_body = self._resume_body(
                    parsed, delivered, cap=cap if in_handoff else 0)
            elif committed:
                # headers are committed but no tokens flowed yet: a
                # from-scratch retry that must still bypass the shed
                # valve (the client can no longer be told 503)
                resend = dict(parsed)
                if in_handoff:
                    resend = json.loads(
                        self._capped_body(parsed, cap).decode())
                resend["resume"] = True
                send_body = json.dumps(resend).encode()
            elif in_handoff:
                send_body = self._capped_body(parsed, cap)
            else:
                send_body = body
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=self.read_timeout_s)
            self._charge(rep, cost)
            try:
                conn.request("POST", rep.path, send_body,
                             self._request_headers(trace, tspan, rep))
                resp = conn.getresponse()
                if resp.status == 503:
                    shed_ra = max(
                        shed_ra or 0.0,
                        self._retry_after_of(dict(resp.getheaders()),
                                             resp.read()))
                    tried_shed.add(rep.rid)
                    attempts.append((rep.rid, "503"))
                    continue
                if resp.status != 200:
                    # validation error — deterministic, no point
                    # retrying elsewhere
                    payload = resp.read()
                    if committed:
                        write_line(json.dumps(
                            {"error": "replica rejected resume: %s"
                                      % payload.decode("utf-8",
                                                       "replace")}
                        ).encode() + b"\n")
                        return
                    raise _ReplicaReject(resp.status, payload)
                if not committed:
                    # the headers commit is a CLIENT-side write: a
                    # client that died before its 200 must abort the
                    # request (_ClientGone), never be misattributed
                    # as a replica failure and cascade mark-downs
                    # across the healthy fleet
                    try:
                        send_headers()
                    except Exception as e:  # noqa: BLE001
                        raise _ClientGone() from e
                    committed = True
                sink = {}
                out = self._pump_stream(resp, parsed, delivered,
                                        write_line, bool(tried_dead),
                                        swallow_done=in_handoff,
                                        sink=sink)
                if out == "handoff":
                    # prefill leg complete: the loop continues in the
                    # decode phase with the delivered prefix — the
                    # exact failover splice, minus the failure
                    with self._lock:
                        self._counters["prefill_handoffs"] += 1
                    flight.record("serve.prefill_handoff",
                                  replica=rep.rid, session=session,
                                  prompt_len=len(parsed["input"][0]
                                                 if isinstance(
                                                     parsed["input"][0],
                                                     list)
                                                 else parsed["input"]),
                                  handoff=len(delivered), stream=True,
                                  trace=trace)
                    if trace:
                        tracing.span_add(trace, "router.handoff",
                                         parent=tspan,
                                         replica=rep.rid,
                                         handoff=len(delivered))
                    # the prefill leg's phases roll up under the
                    # prefill replica; the decode leg owns the stream
                    # remainder
                    self._note_phases(rep, sink.get("phases"))
                    # the decode leg is a shed-exempt resume: replicas
                    # that shed the ORIGINAL submission are eligible
                    tried_shed.clear()
                    continue
                if out:
                    with self._lock:
                        self._counters["routed"] += 1
                    self._note_phases(
                        rep, sink.get("phases"),
                        total_ms=(time.monotonic() - t0) * 1e3,
                        trace=trace, tspan=tspan)
                    return
                # upstream died mid-stream (EOF / error line / reset):
                # fall through to failover below
                raise ConnectionError("replica stream ended before "
                                      "the done line")
            except _ClientGone:
                # the CLIENT vanished: closing the upstream connection
                # (finally below) fails the replica's next write, which
                # cancels the request engine-side — nothing to retry
                return
            except (OSError, ValueError,
                    http.client.HTTPException) as e:
                tried_dead.add(rep.rid)
                # new failover round: shed exclusions reset — the
                # shed-exempt resume may now land on a replica whose
                # valve refused the ORIGINAL (pre-commit) submission
                tried_shed.clear()
                attempts.append((rep.rid, repr(e)[:120]))
                self._mark_down(rep, "stream failed: %r" % (e,))
                self._note_failover(rep, session, attempt, stream=True,
                                    delivered=len(delivered),
                                    trace=trace, tspan=tspan)
                if delivered:
                    with self._lock:
                        self._counters["resumed_streams"] += 1
                if len(delivered) >= max_new:
                    # everything decoded and delivered — only the done
                    # line was lost; synthesize it instead of burning a
                    # replica on a zero-token resume
                    row = parsed["input"]
                    if row and isinstance(row[0], list):
                        row = row[0]
                    synth = {"done": True,
                             "result": [int(t) for t in row]
                             + [int(t) for t in delivered],
                             "resumed": True}
                    if trace:
                        synth["trace"] = trace
                    write_line(json.dumps(synth).encode() + b"\n")
                    with self._lock:
                        self._counters["routed"] += 1
                    return
                attempt += 1
                time.sleep(self.backoff_delay(attempt - 1))
            finally:
                self._charge(rep, -cost)
                conn.close()
        # retry budget exhausted
        ra = shed_ra if shed_ra is not None else 1.0
        msg = ("no replica could complete the stream (attempts: %s)"
               % (attempts,))
        with self._lock:
            self._counters["shed_rejects"] += 1
        if committed:
            write_line(json.dumps(
                {"error": msg, "retry_after_s": ra}).encode() + b"\n")
            return
        raise NoReplicaError(msg, retry_after_s=ra)

    def _pump_stream(self, resp, parsed, delivered, write_line,
                     resumed, swallow_done=False, sink=None):
        """Forward NDJSON lines replica→client until the done line
        (True) or upstream failure (False).  Client write failures
        raise :class:`_ClientGone`.  ``delivered`` accumulates the
        new tokens the client has actually been sent — the splice
        offset a failover resumes from.

        ``swallow_done``: the prefill-handoff leg — the capped
        request's done line is NOT the client's terminal (the decode
        continuation follows on another replica): any tokens it
        carries beyond what token lines delivered are forwarded as
        one more token line, and ``"handoff"`` is returned instead of
        True."""
        while True:
            raw = resp.fp.readline()
            if not raw:
                return False              # EOF before done: upstream died
            try:
                msg = json.loads(raw)
            except ValueError:
                return False              # torn line: upstream died
            if "tokens" in msg:
                self._client_write(write_line, raw)
                delivered.extend(msg["tokens"])
            elif "error" in msg and msg.get("kind") in (
                    "DeadlineExceeded", "RequestCancelled"):
                # REQUEST-scoped terminal: the replica is healthy —
                # one expired deadline or a cancelled slowloris must
                # not flap the whole replica down, and certainly not
                # resume an already-dead request on a survivor.
                # Relay the verdict and end the stream.
                self._client_write(write_line, raw)
                return True
            elif msg.get("done"):
                if sink is not None and isinstance(
                        msg.get("phases"), dict):
                    # the replica's queue/prefill/decode decomposition
                    # rides the done line; harvested for the fleet
                    # rollup even when the line itself is swallowed
                    sink["phases"] = msg["phases"]
                if swallow_done:
                    # the leg's authoritative result covers overflow-
                    # dropped chunks too: hand the client whatever the
                    # token lines didn't, then flip to the decode leg
                    row = parsed["input"]
                    if row and isinstance(row[0], list):
                        row = row[0]
                    tail = [int(t) for t in
                            list(msg.get("result") or [])[
                                len(row) + len(delivered):]]
                    if tail:
                        self._client_write(
                            write_line,
                            json.dumps({"tokens": tail}).encode()
                            + b"\n")
                        delivered.extend(tail)
                    return "handoff"
                # a resumed replica's terminal result is already the
                # full concatenation (its prompt included the
                # delivered prefix); tag splices for observability
                if resumed:
                    msg["resumed"] = True
                    raw = json.dumps(msg).encode() + b"\n"
                self._client_write(write_line, raw)
                return True
            elif "error" in msg:
                return False              # engine-side failure: fail over
            else:
                self._client_write(write_line, raw)

    @staticmethod
    def _client_write(write_line, raw):
        try:
            write_line(raw)
        except Exception as e:  # noqa: BLE001 — dead client socket
            raise _ClientGone() from e

    # -------------------------------------------------- fleet observability
    def _export_fleet_gauges(self):
        """The PR 3 MetricsRegistry surface of the fleet: replica
        count, the manager's desired count, and scale/replace totals
        (``veles_fleet_*``) — rendered on every ``/metrics``-style
        Prometheus endpoint process-wide.  Fail-soft: telemetry must
        never take the router down."""
        try:
            from veles_tpu import telemetry
            if self._gauges is None:
                self._gauges = {
                    "replicas": telemetry.registry.gauge(
                        "veles_fleet_replicas",
                        "registered serving replicas",
                        labelnames=("state",)),
                    "desired": telemetry.registry.gauge(
                        "veles_fleet_desired",
                        "fleet manager's desired replica count"),
                    "scaled": telemetry.registry.counter(
                        "veles_fleet_scale_events_total",
                        "autoscaler decisions executed",
                        labelnames=("direction",)),
                    "replaced": telemetry.registry.counter(
                        "veles_fleet_replaced_total",
                        "replicas replaced after a crash or host "
                        "death"),
                }
            states = {s: 0 for s in (Replica.UP, Replica.DRAINING,
                                     Replica.DOWN)}
            with self._lock:
                fleet = dict(self._fleet or {})
                for rep in self._replicas.values():
                    states[rep.state] = states.get(rep.state, 0) + 1
            for state, n in states.items():
                self._gauges["replicas"].set(n, state=state)
            if fleet.get("desired") is not None:
                self._gauges["desired"].set(fleet["desired"])
        except Exception:   # noqa: BLE001 — fail-soft
            pass

    def note_fleet(self, **fields):
        """The fleet manager's status block (desired count, hosts,
        lost hosts, scale/replace counters...) — merged into
        ``/metrics`` and ``/health`` so one probe of the router
        answers "what does the manager WANT vs what is live"."""
        with self._lock:
            self._fleet = dict(self._fleet or {}, **fields)
        self._export_fleet_gauges()

    def fleet_event(self, kind, direction=None):
        """Account one manager action on the fleet counters:
        ``kind`` is ``"scale"`` (with direction ``"up"``/``"down"``)
        or ``"replace"``."""
        self._export_fleet_gauges()   # ensure instruments exist
        try:
            if self._gauges is None:
                return
            if kind == "scale":
                self._gauges["scaled"].inc(
                    direction=direction or "up")
            elif kind == "replace":
                self._gauges["replaced"].inc()
        except Exception:   # noqa: BLE001 — fail-soft
            pass

    def fleet_signals(self):
        """The autoscaler's input, aggregated from the health probes
        already flowing: the WORST measured queue-wait overshoot any
        replica reports (``SloShedder.overshoot`` via ``/health``),
        the fleet-wide shed total (replica ``serve.shed`` rejections
        plus the router's own all-shed 503s), the summed
        queued-but-unprefilled prompt-token backlog (each replica's
        ``queued_prefill_tokens`` — the EARLY scale-up signal: a
        prefill backlog predicts the queue-wait breach before the
        shedder can measure it), and whether any replica still holds
        queued/in-flight work (the idle signal for scale-down)."""
        with self._lock:
            reps = list(self._replicas.values())
            shed_total = int(self._counters["shed_rejects"])
        overshoot, busy, live, backlog = 0.0, False, 0, 0
        for rep in reps:
            if rep.state == Replica.UP:
                live += 1
            h = rep.last_health or {}
            serving = h.get("serving") or {}
            try:
                overshoot = max(overshoot,
                                float(serving.get("overshoot") or 0.0))
            except (TypeError, ValueError):
                pass
            try:
                shed_total += int(serving.get("shed_total") or 0)
            except (TypeError, ValueError):
                pass
            try:
                backlog += int(h.get("queued_prefill_tokens") or 0)
            except (TypeError, ValueError):
                pass
            if h.get("queued") or h.get("in_flight"):
                busy = True
        return {"overshoot": overshoot, "shed_total": shed_total,
                "prefill_backlog": backlog, "busy": busy,
                "live": live}

    # ------------------------------------------------------------ metrics
    def metrics(self):
        with self._lock:
            counters = dict(self._counters)
            sessions = len(self._sessions)
            fleet = dict(self._fleet) if self._fleet else None
        reps = self.replicas()
        states = {}
        for rep in reps.values():
            states[rep["state"]] = states.get(rep["state"], 0) + 1
        out = {"replicas": reps, "states": states,
               "sessions": sessions, "counters": counters,
               "affinity": self.affinity,
               "retry_max": self.retry_max,
               "health_interval_ms": self.health_interval_s * 1e3,
               "placement": self.placement,
               "cost": self.cost.status()}
        phases = self._phase_rollup()
        if phases:
            out["phases"] = phases
        if fleet is not None:
            out["fleet"] = fleet
        return out

    def _phase_rollup(self):
        """Fleet-wide per-phase latency quantiles, keyed
        ``replica -> phase -> {p50, p99, n}`` — the JSON face of the
        ``veles_fleet_phase_ms`` histograms, assembled from the
        ``phases`` decomposition each replica reports on its done
        lines (plus the router-computed ``stream`` remainder)."""
        with self._lock:
            stats = {k: list(v) for k, v in self._phase_stats.items()}
        out = {}
        for (rid, phase), vals in sorted(stats.items()):
            if not vals:
                continue
            vals.sort()
            rep = out.setdefault(rid, {})
            rep[phase] = {
                "p50": round(vals[len(vals) // 2], 3),
                "p99": round(vals[min(len(vals) - 1,
                                      int(len(vals) * 0.99))], 3),
                "n": len(vals),
            }
        return out

    def trace_timeline(self, tid):
        """Aggregate one request's spans across the fleet: the
        router's own span store (root/leg/failover/handoff spans)
        merged with every live replica's ``/trace/<id>`` answer.
        A dead replica simply contributes nothing — absence is not a
        gap, the router-side chain stays connected (that is what
        makes post-SIGKILL timelines reconstructable live).
        Fail-soft per replica: one unreachable endpoint must not
        block the reconstruction."""
        if not tracing.valid_id(tid):
            return None
        spans = list(tracing.store.spans(tid))
        with self._lock:
            reps = [r for r in self._replicas.values()
                    if r.state in (Replica.UP, Replica.DRAINING)]
        seen = {s.get("span") for s in spans}
        for rep in reps:
            try:
                conn = http.client.HTTPConnection(
                    rep.host, rep.port, timeout=self.read_timeout_s)
                try:
                    conn.request("GET",
                                 rep.path + "/trace/" + tid)
                    resp = conn.getresponse()
                    if resp.status != 200:
                        continue
                    payload = json.loads(resp.read())
                finally:
                    conn.close()
            except (OSError, ValueError,
                    http.client.HTTPException):
                continue
            for span in payload.get("spans") or []:
                if span.get("span") in seen:
                    continue
                seen.add(span.get("span"))
                spans.append(span)
        if not spans:
            return None
        spans.sort(key=lambda s: s.get("ts") or 0.0)
        verdict = tracing.validate(spans)
        return {"trace": tid, "spans": spans,
                "phases": tracing.phases_of(spans),
                "gapless": verdict["ok"],
                "problems": verdict["problems"]}

    def fleet_health(self):
        reps = self.replicas()
        live = sum(1 for r in reps.values() if r["state"] == "up")
        out = {"state": "serving" if live else "unavailable",
               "live_replicas": live, "replicas": reps}
        with self._lock:
            if self._fleet:
                out["fleet"] = dict(self._fleet)
        return out

    # ------------------------------------------------------------- server
    def start(self):
        router = self

        from veles_tpu.services.restful import send_json

        class Handler(BaseHTTPRequestHandler):
            def _send_json(self, code, payload, headers=()):
                send_json(self, code, payload, headers)

            def do_GET(self):
                if self.path == router.path + "/metrics":
                    self._send_json(200, router.metrics())
                elif self.path == router.path + "/health":
                    h = router.fleet_health()
                    self._send_json(
                        200 if h["state"] == "serving" else 503, h)
                elif self.path.startswith(
                        router.path + "/trace/"):
                    tid = self.path[len(router.path + "/trace/"):]
                    tl = router.trace_timeline(tid)
                    if tl is None:
                        self._send_json(
                            404, {"error": "unknown trace",
                                  "trace": tid})
                    else:
                        self._send_json(200, tl)
                else:
                    self.send_error(404)

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(length)
                    if self.path == router.path + "/register":
                        req = json.loads(body)
                        rid = router.register(req["url"],
                                              role=req.get("role"))
                        self._send_json(200, {"replica": rid})
                        return
                    if self.path == router.path + "/deregister":
                        req = json.loads(body)
                        ok = router.deregister(
                            rid=req.get("replica"),
                            url=req.get("url"))
                        self._send_json(200 if ok else 404,
                                        {"removed": ok})
                        return
                    if self.path == router.path + "/drain":
                        req = json.loads(body)
                        ok = router.drain_replica(req.get("replica"))
                        self._send_json(202 if ok else 404,
                                        {"draining": ok})
                        return
                    if self.path != router.path:
                        self.send_error(404)
                        return
                    self._route(body)
                except NoReplicaError as e:
                    self._send_json(
                        503, {"error": str(e),
                              "retry_after_s": e.retry_after_s},
                        headers=[("Retry-After", str(max(
                            1, int(math.ceil(e.retry_after_s)))))])
                except _ReplicaReject as e:
                    self.send_response(e.status)
                    self.send_header("Content-Type",
                                     "application/json")
                    self.send_header("Content-Length",
                                     str(len(e.payload)))
                    self.end_headers()
                    self.wfile.write(e.payload)
                except Exception as e:  # noqa: BLE001 — report to client
                    try:
                        self._send_json(400, {"error": str(e)})
                    except Exception:  # noqa: BLE001 — dead pipe
                        pass

            def _route(self, body):
                parsed = json.loads(body)
                if isinstance(parsed, dict) \
                        and parsed.pop("resume", None):
                    # "resume" is the ROUTER-internal shed-exemption
                    # flag for failover continuations — strip it from
                    # client input so nobody rides past the fleet's
                    # admission control by forging it
                    body = json.dumps(parsed).encode()
                session = parsed.get("session")
                # the router is the trace EDGE: it always mints — an
                # incoming X-Veles-Trace header is a forgery here
                # (only replica hops are mid-chain) and is ignored,
                # the same trust boundary as the resume strip above
                trace = tracing.new_trace_id()
                tspan = tracing.span_add(
                    trace, "request", edge="router",
                    session=session)
                t_edge = time.monotonic()
                try:
                    self._route_traced(parsed, body, session,
                                       trace, tspan)
                finally:
                    # the minter owns the request's ONE terminal
                    # span, on every exit path (success, failover
                    # exhaustion, dead client)
                    tracing.span_add(
                        trace, "request.done", parent=tspan,
                        terminal=True,
                        dur_ms=round(
                            (time.monotonic() - t_edge) * 1e3, 3))

            def _route_traced(self, parsed, body, session, trace,
                              tspan):
                if isinstance(parsed.get("generate"), dict) \
                        and parsed["generate"].get("stream"):
                    def send_headers():
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/x-ndjson")
                        self.end_headers()

                    def write_line(raw):
                        self.wfile.write(raw)
                        self.wfile.flush()

                    router.route_stream(parsed, body, session,
                                        send_headers, write_line,
                                        trace=trace, tspan=tspan)
                    return
                status, payload, headers = router.route_buffered(
                    body, session=session, parsed=parsed,
                    trace=trace, tspan=tspan)
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, fmt, *args):
                router.debug("http: " + fmt, *args)

        class Server(ThreadingHTTPServer):
            # survive concurrent client bursts (same rationale as the
            # replica endpoint)
            request_queue_size = 128

        self._server = Server((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="VelesFleetRouter")
        self._thread.start()
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True,
            name="VelesFleetHealth")
        self._health_thread.start()
        self.info("fleet router on http://%s:%d%s", self.host,
                  self.port, self.path)

    def stop(self):
        self._closed = True
        self._health_wake.set()
        if self._server is not None:
            self._server.shutdown()
            self._server = None
        if self._health_thread is not None:
            self._health_thread.join(timeout=5)
            self._health_thread = None
        for api in self._local_apis:
            try:
                api.stop()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        self._local_apis = []


class _ClientGone(Exception):
    """The downstream client's socket died mid-stream — abort the
    upstream leg (its write failure cancels the engine request) and
    stop; never retried."""


class _ReplicaReject(Exception):
    """A replica answered with a deterministic non-200/503 (validation
    400, deadline 504) — propagate its verdict verbatim instead of
    burning retries on an error every replica will repeat."""

    def __init__(self, status, payload):
        super(_ReplicaReject, self).__init__(
            "replica rejected the request (%d)" % status)
        self.status = int(status)
        self.payload = bytes(payload)


def main(argv=None):
    """``veles-tpu-router``: stand up a fleet router over replica
    URLs.  Replicas can also register themselves later via POST
    ``{path}/register``."""
    import argparse
    ap = argparse.ArgumentParser(
        description="health-routed fleet router over engine replicas "
                    "(docs/services.md 'Fleet serving')")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8185)
    ap.add_argument("--path", default="/fleet")
    ap.add_argument("--replica", action="append", default=[],
                    metavar="URL",
                    help="replica work URL (repeatable), e.g. "
                         "http://127.0.0.1:8180/service")
    ap.add_argument("--health-interval-ms", type=float, default=None)
    ap.add_argument("--retry-max", type=int, default=None)
    ap.add_argument("--affinity", choices=("session", "none"),
                    default=None)
    args = ap.parse_args(argv)
    router = FleetRouter(host=args.host, port=args.port,
                         path=args.path,
                         health_interval_ms=args.health_interval_ms,
                         retry_max=args.retry_max,
                         affinity=args.affinity)
    for url in args.replica:
        router.register(url)
    router.start()
    print("fleet router on http://%s:%d%s (%d replicas)"
          % (router.host, router.port, router.path,
             len(args.replica)))
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        router.stop()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
