"""Request lifecycle for the serving plane (``services.restful``).

The survival layer under ``ContinuousEngine``: every request carries an
id, an optional deadline, and a cancel path, and the pieces that keep a
loaded server alive live here —

* :class:`BoundedStream` — the engine→HTTP-worker token channel.
  Replaces the unbounded ``queue.Queue`` the streaming path used to
  accumulate into when a client stopped reading: capacity is fixed and
  overflow either drops the oldest chunk (``drop_oldest``, the default
  — the terminal ``done`` line still carries the full result) or
  applies per-request backpressure (``block``: ``push`` refuses while
  full, the engine holds that request's chunks back and retries next
  dispatch — NEVER sleeping on the engine thread, which other
  requests' decodes share — and cancels the request once it has made
  no progress for the stall timeout: the consumer is dead or a
  slowloris).
* :class:`SloShedder` — closed-loop admission control.  Watches the
  MEASURED queue wait (the ``serve.submit`` → ``serve.admit`` gap the
  flight recorder already records) plus the head-of-line wait of the
  oldest still-queued request; past ``root.common.serve
  .slo_queue_wait_ms`` new work is rejected with
  :class:`ShedError` (HTTP 503 + ``Retry-After``) instead of queuing
  into a breach, and admission reopens once the wait falls back under
  ``close_fraction`` of the SLO (hysteresis, so the valve does not
  chatter at the threshold).
* :class:`DrainState` — the graceful-shutdown state machine every
  serving surface consults: ``serving`` → ``draining`` (stop
  admission, finish in-flight) → ``drained`` (safe to exit /
  deregister).  SIGTERM on a serve process and the fleet router's
  ``/drain`` admin both drive it.
* the terminal exception types the REST layer maps to status codes:
  :class:`ShedError` → 503, :class:`DeadlineExceeded` → 504,
  :class:`RequestCancelled` → the stream's error line.

Everything here is plain-Python and thread-safe by construction: the
engine thread is the only producer, HTTP workers are the consumers,
and the shedder is read lock-free on the submit path.
"""

import collections
import threading
import time


class ShedError(RuntimeError):
    """Raised at submit while the admission controller is shedding:
    the measured queue wait exceeds the configured SLO, so queueing
    this request would only widen the breach.  ``retry_after_s`` is
    the client's backoff hint (HTTP ``Retry-After``)."""

    def __init__(self, message, retry_after_s=1.0):
        super(ShedError, self).__init__(message)
        self.retry_after_s = float(retry_after_s)


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before it could complete: either
    it was never admitted in time, or it was cancelled mid-decode —
    decoding tokens nobody can use anymore wastes the pool."""


class RequestCancelled(RuntimeError):
    """The request was cancelled — explicit ``cancel(req_id)``, a
    client disconnect detected on a failed stream write, or a stalled
    stream consumer in ``block`` overflow mode."""


class EngineUnavailable(RuntimeError):
    """The serving engine/batcher is not accepting work (stopped, or
    stopping).  The REST layer maps this to **503** — it is service
    unavailability, not a client error: a fleet router must route
    around it (and retry), exactly like a shed valve, never propagate
    it as a deterministic 400."""


class BoundedStream(object):
    """Bounded chunk channel between the engine thread (producer) and
    one HTTP worker (consumer).

    ``push`` NEVER sleeps — the producer is the engine thread, whose
    loop every request's decode shares.  It returns False only when a
    ``block``-overflow channel is full (the caller keeps the chunk,
    retries next dispatch, and gives up on the request once it has
    made no progress for its stall budget); ``drop_oldest`` discards
    the oldest un-read chunk instead and always accepts.
    ``put_terminal`` ALWAYS succeeds regardless of capacity: a
    terminal (`done`/`error`) must reach the consumer or it blocks in
    ``get`` forever.  ``dropped`` counts chunks discarded by
    ``drop_oldest``."""

    OVERFLOW = ("drop_oldest", "block")

    def __init__(self, capacity=64, overflow="drop_oldest"):
        if overflow not in self.OVERFLOW:
            raise ValueError("overflow must be one of %s, got %r"
                             % (self.OVERFLOW, overflow))
        self.capacity = max(1, int(capacity))
        self.overflow = overflow
        self.dropped = 0
        self._items = collections.deque()
        self._closed = False
        self._cond = threading.Condition()

    def push(self, item):
        """Producer side, non-blocking.  Returns False iff a
        ``block``-mode channel is full (retry next dispatch)."""
        with self._cond:
            if self._closed:
                return True               # terminal already delivered
            if len(self._items) >= self.capacity:
                if self.overflow != "drop_oldest":
                    return False
                self._items.popleft()
                self.dropped += 1
            self._items.append(item)
            self._cond.notify_all()
        return True

    def put_terminal(self, item):
        """Deliver the terminal chunk unconditionally (never dropped,
        never blocked) and close the channel; later pushes no-op."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._items.append(item)
            self._cond.notify_all()

    def get(self, timeout=None):
        """Consumer side: next chunk, blocking.  Raises ``TimeoutError``
        if ``timeout`` elapses with nothing queued."""
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        with self._cond:
            while not self._items:
                left = (None if deadline is None
                        else deadline - time.monotonic())
                if left is not None and left <= 0:
                    raise TimeoutError("BoundedStream.get timed out")
                if not self._cond.wait(left):
                    raise TimeoutError("BoundedStream.get timed out")
            item = self._items.popleft()
            self._cond.notify_all()       # wake a blocked producer
        return item

    def qsize(self):
        with self._cond:
            return len(self._items)


class SloShedder(object):
    """Closed-loop SLO admission controller.

    The engine feeds it two signals: ``note_admit(queue_wait_ms)`` —
    the measured wait of every request the pool just admitted (the
    same number the ``serve.admit`` flight event carries) — and
    ``update(head_wait_ms)`` once per engine loop with the
    head-of-line wait of the oldest request still queued (a LOWER
    bound on that request's eventual wait, which is what keeps the
    valve responsive when the pool is so far behind that nothing is
    being admitted at all).

    Opens when either signal crosses ``slo_ms``; closes when both
    fall back under ``close_fraction * slo_ms`` (hysteresis).  While
    open, ``should_shed()`` is True and submit rejects with
    :class:`ShedError`.  ``slo_ms <= 0`` disables the controller
    entirely (``enabled`` False, never sheds)."""

    def __init__(self, slo_ms, close_fraction=0.5,
                 overshoot_cap=None):
        self.slo_ms = float(slo_ms or 0)
        self.close_fraction = min(1.0, max(0.0, float(close_fraction)))
        if overshoot_cap is None:
            from veles_tpu.config import root
            overshoot_cap = root.common.serve.get(
                "retry_after_overshoot_cap", 8.0)
        self.overshoot_cap = max(1.0, float(overshoot_cap))
        self._fresh_admit_ms = None       # consumed by the next update
        self._last_measure_ms = 0.0       # latest control-loop input
        self._open = False
        self.shed_total = 0
        self.open_total = 0
        self._lock = threading.Lock()

    @property
    def enabled(self):
        return self.slo_ms > 0

    def should_shed(self):
        """Lock-free read for the submit hot path."""
        return self._open

    def note_admit(self, queue_wait_ms):
        with self._lock:
            self._fresh_admit_ms = max(float(queue_wait_ms),
                                       self._fresh_admit_ms or 0.0)

    def update(self, head_wait_ms=0.0):
        """One control-loop step.  Returns ``"open"`` / ``"close"`` on
        a transition (the engine records the flight event), else
        None.

        Admit measurements influence exactly ONE control step (the
        worst since the previous ``update`` call, then consumed):
        a breach-sized wait must be able to open the valve even when
        the head of the queue is empty again, but a STALE sample from
        the overload's peak must not hold the valve open after the
        queue has drained — head-of-line wait is the live signal on
        the close side."""
        if not self.enabled:
            return None
        with self._lock:
            fresh = self._fresh_admit_ms
            self._fresh_admit_ms = None
        measure = max(float(head_wait_ms), fresh or 0.0)
        self._last_measure_ms = measure
        if not self._open and measure > self.slo_ms:
            self._open = True
            self.open_total += 1
            return "open"
        # <= so close_fraction=0 means "close once fully drained"
        # (measure bottoms out at exactly 0.0) instead of latching
        # the valve open forever
        if self._open and measure <= self.close_fraction * self.slo_ms:
            self._open = False
            return "close"
        return None

    def shed(self):
        """Account one rejected request; returns the backoff hint."""
        with self._lock:
            self.shed_total += 1
        return self.retry_after_s()

    def retry_after_s(self):
        """Client backoff hint, scaled with the measured overshoot: at
        least one SLO window and at least a second — by construction
        the breach needs at least that long to drain below the close
        threshold — times how far the last measured queue wait sits
        past the SLO (a replica at 4x the SLO pushes clients, and the
        fleet router, away for ~4 windows), capped at
        ``overshoot_cap`` windows so a pathological spike cannot send
        clients away for hours."""
        base = max(1.0, self.slo_ms / 1000.0)
        if self.slo_ms <= 0:
            return base
        overshoot = min(self.overshoot_cap,
                        max(1.0, self._last_measure_ms / self.slo_ms))
        return base * overshoot

    def overshoot(self):
        """The last measured queue wait as a fraction of the SLO (1.0
        = exactly at it, 0.0 while disabled or idle) — the serving
        fleet's scale-up signal: the autoscaler reads it off every
        replica's ``/health`` (through :meth:`status`) and adds
        capacity when the measured wait sits past the SLO instead of
        letting the shed valve turn traffic away forever."""
        if not self.enabled:
            return 0.0
        return self._last_measure_ms / self.slo_ms

    def status(self):
        return {"enabled": self.enabled,
                "state": ("open" if self._open else "closed")
                if self.enabled else "disabled",
                "slo_ms": self.slo_ms,
                "last_measure_ms": round(self._last_measure_ms, 3),
                "overshoot": round(self.overshoot(), 4),
                "shed_total": self.shed_total,
                "open_total": self.open_total}


class DrainState(object):
    """Graceful-shutdown state machine for one serving endpoint:
    ``serving`` → ``draining`` → ``drained``, monotonic.

    ``begin()`` flips admission off (the REST layer rejects new work
    with 503 + Retry-After while not ``serving``); whoever watches the
    in-flight population calls ``finish()`` once it hits zero, and
    ``wait()`` lets a SIGTERM handler or the fleet router block until
    the endpoint is safe to kill/deregister.  Thread-safe; both
    transitions are idempotent (False on a no-op)."""

    ORDER = ("serving", "draining", "drained")

    def __init__(self):
        self._cond = threading.Condition()
        self._state = "serving"
        self.reason = None
        self.since = None                 # monotonic of begin()

    @property
    def state(self):
        return self._state

    def is_serving(self):
        return self._state == "serving"

    def begin(self, reason="drain"):
        """serving → draining.  Returns True on the transition."""
        with self._cond:
            if self._state != "serving":
                return False
            self._state = "draining"
            self.reason = str(reason)
            self.since = time.monotonic()
            self._cond.notify_all()
        return True

    def finish(self):
        """draining → drained.  Returns True on the transition."""
        with self._cond:
            if self._state != "draining":
                return False
            self._state = "drained"
            self._cond.notify_all()
        return True

    def wait(self, state="drained", timeout=None):
        """Block until the machine reaches (or has passed) ``state``;
        True iff reached within ``timeout``."""
        want = self.ORDER.index(state)
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        with self._cond:
            while self.ORDER.index(self._state) < want:
                left = (None if deadline is None
                        else deadline - time.monotonic())
                if left is not None and left <= 0:
                    return False
                self._cond.wait(left)
        return True

    def status(self):
        out = {"state": self._state}
        if self.since is not None:
            out["reason"] = self.reason
            out["draining_s"] = round(time.monotonic() - self.since, 3)
        return out
