"""ctypes bridge to the native C++ inference runtime (ref libVeles usage:
embedded apps link the C++ engine; here Python drives it for round-trip
tests — the same Python↔C++ package contract the reference tested with
libVeles/tests fixtures)."""

import ctypes
import os
import subprocess

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libveles_native.so")

_lib = None


def build(force=False):
    """Build libveles_native.so via make (g++ is in the base image).
    Always invokes make — the Makefile's header dependencies make the
    call a no-op when the .so is current, and a rebuild when any source
    changed (a stale committed .so must never mask source edits)."""
    if force and os.path.exists(_LIB_PATH):
        os.remove(_LIB_PATH)
    subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                   capture_output=True)
    return _LIB_PATH


def _load():
    global _lib
    if _lib is not None:
        return _lib
    build()
    lib = ctypes.CDLL(_LIB_PATH)
    lib.veles_native_load.restype = ctypes.c_void_p
    lib.veles_native_load.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                      ctypes.c_int]
    lib.veles_native_input_size.argtypes = [ctypes.c_void_p]
    lib.veles_native_output_size.argtypes = [ctypes.c_void_p]
    lib.veles_native_num_units.argtypes = [ctypes.c_void_p]
    lib.veles_native_unit_name.restype = ctypes.c_char_p
    lib.veles_native_unit_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.veles_native_arena_bytes.restype = ctypes.c_long
    lib.veles_native_arena_bytes.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.veles_native_infer.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_float), ctypes.c_int,
        ctypes.POINTER(ctypes.c_float)]
    lib.veles_native_generate.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.c_int, ctypes.POINTER(ctypes.c_int), ctypes.c_char_p,
        ctypes.c_int]
    lib.veles_native_generate_sampled.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.c_int, ctypes.c_float, ctypes.c_int, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int), ctypes.c_char_p, ctypes.c_int]
    lib.veles_native_free.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


class NativeWorkflow(object):
    """Loaded native inference engine for an exported package."""

    def __init__(self, package_path):
        lib = _load()
        err = ctypes.create_string_buffer(512)
        self._h = lib.veles_native_load(
            package_path.encode(), err, len(err))
        if not self._h:
            raise RuntimeError("native load failed: %s"
                               % err.value.decode())
        self._lib = lib
        self.input_size = lib.veles_native_input_size(self._h)
        self.output_size = lib.veles_native_output_size(self._h)

    @property
    def unit_names(self):
        n = self._lib.veles_native_num_units(self._h)
        return [self._lib.veles_native_unit_name(self._h, i).decode()
                for i in range(n)]

    def arena_bytes(self, batch=1):
        return int(self._lib.veles_native_arena_bytes(self._h, batch))

    def __call__(self, x):
        x = np.ascontiguousarray(x, np.float32).reshape(len(x), -1)
        if x.shape[1] != self.input_size:
            raise ValueError("expected %d input features, got %d"
                             % (self.input_size, x.shape[1]))
        out = np.empty((len(x), self.output_size), np.float32)
        rc = self._lib.veles_native_infer(
            self._h, x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            len(x), out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if rc:
            raise RuntimeError("native inference failed")
        return out

    def generate(self, prompt, max_new, temperature=0.0, top_k=0,
                 seed=0):
        """Decode entirely in C++ (causal LM packages): prompt int
        tokens → np.int32 [prompt + generated], capped at the
        package's exported context length.  ``temperature=0`` (or
        ``top_k=1``) is greedy — token-exact vs the Python decoder
        (positions stream through per-block k/v caches, O(T) per
        token, bit-identical to the full causal forward).
        ``temperature>0`` samples softmax(logits/T), optionally
        top_k-truncated, from a seeded xorshift64* stream — the
        stream is deliberately NOT the Python sampler's threefry, so
        sampled tokens differ across the two runtimes by design."""
        prompt = np.ascontiguousarray(np.asarray(prompt).ravel(),
                                      np.int32)
        t_max = self.input_size
        out = np.empty(t_max, np.int32)
        err = ctypes.create_string_buffer(512)
        n = self._lib.veles_native_generate_sampled(
            self._h, prompt.ctypes.data_as(
                ctypes.POINTER(ctypes.c_int)), len(prompt),
            int(max_new), float(temperature), int(top_k), int(seed),
            out.ctypes.data_as(
                ctypes.POINTER(ctypes.c_int)), err, len(err))
        if n < 0:
            raise RuntimeError("native generate failed: %s"
                               % err.value.decode())
        return out[:n].copy()

    def close(self):
        if self._h:
            self._lib.veles_native_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
