"""Snapshotter — periodic checkpoint + resume (ref: veles/snapshotter.py).

The reference pickled the *entire workflow object graph* (topology + weights
+ loader position + RNG states, SURVEY.md §3.5).  The TPU-native equivalent
checkpoints *state, not code*: params, optimizer velocity, loader position,
named-PRNG counters, decision bookkeeping — restored into a freshly
constructed workflow (config-addressed topology).  Kept from the reference:
interval gating by runs AND wall seconds (ref snapshotter.py:159-174),
codecs none/gz/bz2/xz (ref :365-380), and the ``_current`` symlink
(ref :397-409)."""

import bz2
import gzip
import hashlib
import json
import lzma
import os
import pickle
import time

import jax

from veles_tpu import prng
from veles_tpu.config import root
from veles_tpu.registry import MappedRegistry
from veles_tpu.units import Unit, UnitRegistry

CODECS = {
    "": (lambda p: open(p, "wb"), lambda p: open(p, "rb"), ""),
    "gz": (lambda p: gzip.open(p, "wb"), lambda p: gzip.open(p, "rb"),
           ".gz"),
    "bz2": (lambda p: bz2.open(p, "wb"), lambda p: bz2.open(p, "rb"),
            ".bz2"),
    "xz": (lambda p: lzma.open(p, "wb"), lambda p: lzma.open(p, "rb"),
           ".xz"),
}

#: sidecar filename suffix for the per-checkpoint integrity manifest
MANIFEST_SUFFIX = ".manifest.json"
MANIFEST_FORMAT = 1


class SnapshotIntegrityError(ValueError):
    """A checkpoint failed its integrity manifest — torn commit,
    truncation, or bit rot.  Restore paths treat it exactly like any
    other load failure: quarantine and step back to the previous
    commit (``--snapshot auto`` / the supervisor restart loop)."""


class SnapshotNonFiniteError(SnapshotIntegrityError):
    """A commit was REFUSED because the params/velocity trees contain
    NaN/inf.  Committing a poisoned state would poison every future
    restart: the restart loops (supervisor, pod master) would faithfully
    resume divergence forever.  Refusing the commit turns silent
    corruption — a numerics bug, or the memory-corruption class of
    environment fault observed on sandboxed CPU pods — into a loud
    death of THIS life: the last committed checkpoint stays finite, the
    restart machinery replays from it (exact when the fault was
    transient), and the deterministic-bug valve bounds a real NaN bug.
    Disable per-run with ``root.common.snapshot.reject_nonfinite=False``
    for workloads that legitimately checkpoint non-finite leaves."""


class SnapshotReshardError(SnapshotIntegrityError):
    """A checkpoint cannot legally restore onto the live mesh topology
    (:func:`reshard_state`): e.g. the recorded global minibatch does
    not divide the new data-axis size, or a model-parallel axis
    changed.  Raised BEFORE any state is applied — the workflow stays
    untouched."""


def mesh_topology(mesh_config=None):
    """The live run's checkpoint-topology tag: how many processes and
    devices wrote it, and under which mesh axes.  Recorded in every
    commit (state + manifest) so the elastic-pod restore path
    (:func:`reshard_state`) can prove a cross-topology resume legal —
    and so post-mortems can attribute a checkpoint to the pod size
    that produced it."""
    import jax
    tag = {"processes": int(jax.process_count()),
           "devices": int(jax.device_count())}
    if mesh_config is not None:
        tag["axes"] = {str(k): int(v)
                       for k, v in dict(mesh_config.mesh.shape).items()}
        tag["fsdp"] = bool(mesh_config.fsdp)
    return tag


def reshard_state(state, target, minibatch_size=None):
    """Remap a checkpoint written under one mesh topology onto another
    (the elastic-pod degrade/re-expand path, services.podmaster).

    The file/db backends gather every array to the host before
    committing (``host_params``/``host_velocity``) and the orbax import
    restores to host numpy, so params and optimizer slots are **dense,
    topology-free trees** — resharding them is re-placement under the
    new mesh's shardings (``load_params``/``shard_params`` does that),
    per-leaf bit-exact by construction.  What this function owns is
    proving the *rest* of the state stays deterministic at the new size
    and refusing the restore when it cannot:

    * **loader offsets** — the loader serves GLOBAL minibatch indices
      (one shared order/offset, sharded across the data axis inside the
      step), so the global data order is invariant under a resize *iff*
      the new data-axis size still divides the recorded global
      minibatch.  Checked here; violation raises :class:`SnapshotReshardError`
      instead of the trainer's later divisibility error mid-restore.
    * **PRNG words** — every stream is a global ``(seed, counter)``
      pair (veles_tpu.prng), never folded by process index, so the
      words carry unchanged and replay identically on any topology.
      Verified (a per-process word would be a dict keyed off hosts).
    * **model-parallel axes** — parameters are dense in the checkpoint,
      so even a model-axis change is *representable*; it is still
      refused unless sizes match, because tensor-parallel layouts are
      woven into kernels (same policy as
      :func:`parallel.mesh.fit_axes_to_devices`).

    :param state: the loaded snapshot dict (mutated only by dropping
        nothing — returned as-is).
    :param target: a :func:`mesh_topology`-shaped dict for the LIVE
        run.
    :param minibatch_size: the live loader's global minibatch when the
        checkpoint predates the recorded one (legacy).
    :returns: ``(state, report)`` — report carries ``from``/``to``,
        ``changed`` and the list of executed ``checks``."""
    source = state.get("topology")
    report = {"from": source, "to": target, "checks": [],
              "changed": bool(source) and source != target}
    if source and target:
        s_axes, t_axes = source.get("axes"), target.get("axes")
        if s_axes and t_axes:
            for name in sorted(set(s_axes) | set(t_axes)):
                if name == "data":
                    continue
                if s_axes.get(name, 1) != t_axes.get(name, 1):
                    raise SnapshotReshardError(
                        "checkpoint written under %s=%d cannot restore "
                        "onto %s=%d: only the data axis may resize "
                        "(tensor/seq/expert layouts are woven into the "
                        "kernels)" % (name, s_axes.get(name, 1), name,
                                      t_axes.get(name, 1)))
            report["checks"].append("non-data axes match")
        if bool(source.get("fsdp")) != bool(target.get("fsdp")):
            # legal: fsdp only changes array PLACEMENT, the dense host
            # trees re-place under whatever the live mesh wants
            report["checks"].append("fsdp changed (placement-only)")
    loader = state.get("loader")
    if isinstance(loader, dict):
        mb = loader.get("minibatch_size", minibatch_size)
        # only a MESHED run shards the batch across a data axis; a
        # meshless restore serves the whole global minibatch from one
        # process, so there is nothing to divide
        data = (target or {}).get("axes", {}).get("data", 0)
        if mb and data and int(mb) % int(data):
            raise SnapshotReshardError(
                "the new data-axis size %d does not divide the global "
                "minibatch %d — the resized mesh cannot serve the "
                "recorded data order deterministically (choose a pod "
                "size whose data axis divides the minibatch)"
                % (data, mb))
        report["checks"].append(
            "loader offset %s global (order invariant)"
            % loader.get("minibatch_offset"))
    prng_words = state.get("prng")
    if isinstance(prng_words, dict):
        bad = [name for name, st in prng_words.items()
               if not (isinstance(st, dict) and "seed" in st
                       and "counter" in st)]
        if bad:
            raise SnapshotReshardError(
                "prng stream(s) %s are not global (seed, counter) "
                "words — cannot prove their replay is topology-free"
                % bad[:5])
        report["checks"].append("%d prng streams are global words"
                                % len(prng_words))
    import numpy as np
    n_arrays = 0
    for key in ("params", "velocity"):
        tree = state.get(key)
        if tree is None:
            continue
        for path, leaf in iter_state_leaves(tree, "/" + key):
            if hasattr(leaf, "shape"):
                n_arrays += 1
                if not isinstance(leaf, (np.ndarray, np.generic)):
                    # a live jax.Array pinned to the WRITING mesh would
                    # re-place wrong; every import path returns numpy
                    raise SnapshotReshardError(
                        "%s is not a host array (%s) — the checkpoint "
                        "carries device placement from the old "
                        "topology" % (path, type(leaf).__name__))
    report["checks"].append("%d param/slot leaves dense on host"
                            % n_arrays)
    return state, report


def iter_state_leaves(obj, prefix=""):
    """Flatten nested dict/list/tuple snapshot state into sorted
    (path, leaf) pairs — shared by the integrity manifest below and
    scripts.compare_snapshots' leaf-by-leaf diff, so "what the
    verifier compares" and "what the manifest checksums" can never
    drift apart."""
    if isinstance(obj, dict):
        for k in sorted(obj, key=str):
            yield from iter_state_leaves(obj[k], "%s/%s" % (prefix, k))
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            yield from iter_state_leaves(v, "%s[%d]" % (prefix, i))
    else:
        yield prefix or "/", obj


def _leaf_digest(value):
    """Checksum one state leaf.  Arrays hash their raw bytes (plus
    shape/dtype so a reinterpreted buffer can't pass); everything else
    hashes its repr — exact for python scalars, which repr round-trips
    bit-perfectly."""
    import numpy as np
    if isinstance(value, np.ndarray) or isinstance(value, np.generic):
        a = np.ascontiguousarray(value)
        return {"sha256": hashlib.sha256(a.tobytes()).hexdigest(),
                "shape": list(a.shape), "dtype": str(a.dtype)}
    return {"sha256": hashlib.sha256(repr(value).encode()).hexdigest()}


def commit_meta(state=None):
    """Provenance of one checkpoint commit — which process, host, and
    *life* (pod incarnation) wrote it.  Recorded in every backend's
    manifest so the pod master's cross-host checkpoint agreement and
    ``veles-tpu-blackbox`` timelines can attribute each commit
    (``VELES_TPU_INCARNATION`` is threaded into workers by the pod
    agents; standalone runs simply omit it)."""
    import socket

    from veles_tpu.telemetry.flight import _process_index
    meta = {"process_index": _process_index(),
            "hostname": socket.gethostname(),
            "pid": os.getpid()}
    inc = os.environ.get("VELES_TPU_INCARNATION")
    if inc is not None:
        try:
            meta["incarnation"] = int(inc)
        except ValueError:
            meta["incarnation"] = inc
    if isinstance(state, dict) and "epoch" in state:
        meta["epoch"] = state["epoch"]
    if isinstance(state, dict) and "topology" in state:
        # the mesh the commit was written under — the pod master's
        # degraded/re-expand accounting and reshard-on-restore read it
        # without unpickling (scan_commits)
        meta["topology"] = state["topology"]
    if isinstance(state, dict) and state.get("health") is not None:
        # the sentinel's health stamp (services.sentinel): "healthy"
        # or "unhealthy:<kind>" — surfaced by scan_commits without
        # unpickling, so the in-process rollback and the pod-wide
        # agreement can prefer healthy restart points
        meta["health"] = state["health"]
    return meta


def state_manifest(state):
    """Per-leaf checksum manifest of a snapshot state dict (plus the
    :func:`commit_meta` provenance fields)."""
    man = {"format": MANIFEST_FORMAT,
           "created": time.time(),
           "leaves": {path: _leaf_digest(v)
                      for path, v in iter_state_leaves(state)}}
    man.update(commit_meta(state))
    return man


def validate_state_manifest(state, manifest, source="snapshot"):
    """Recompute every leaf digest of a loaded state and compare with
    its manifest; raises :class:`SnapshotIntegrityError` naming the
    first few mismatches."""
    recorded = manifest.get("leaves", {})
    live = {path: _leaf_digest(v) for path, v in iter_state_leaves(state)}
    bad = []
    for path in sorted(set(recorded) | set(live)):
        if recorded.get(path) != live.get(path):
            bad.append(path)
    if bad:
        raise SnapshotIntegrityError(
            "%s failed its integrity manifest: %d leaf mismatch(es), "
            "first: %s" % (source, len(bad), ", ".join(bad[:5])))


def _surface_nonfinite(prefix, bad):
    """Shared surfacing for BOTH reject_nonfinite valves (file/db base
    path and the orbax device-side check): flight event, registry
    counter, and the /api/health degraded flag.  Fail-soft — the VALVE
    fires regardless of telemetry state."""
    from veles_tpu.telemetry import flight
    flight.record("snapshot.nonfinite", leaves=bad[:8], prefix=prefix)
    try:
        from veles_tpu import telemetry
        telemetry.registry.counter(
            "veles_snapshot_nonfinite_total",
            "checkpoint commits refused by the reject_nonfinite "
            "poison valve").inc()
        telemetry.health.note_nonfinite_commit(prefix=prefix,
                                               leaves=bad[:5])
    except Exception:   # noqa: BLE001
        pass


def _file_sha256(path):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _load_manifest(path):
    """The checkpoint's manifest sidecar, or None (legacy checkpoint,
    unreadable sidecar — both degrade to unvalidated load)."""
    try:
        with open(path + MANIFEST_SUFFIX) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _write_json_atomic(path, payload):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1)
    os.replace(tmp, path)


# ---------------------------------------------------------------------
# cross-host checkpoint agreement (the pod tier, services.podmaster)
#
# In multi-controller SPMD every host commits its own checkpoint copy
# (``per_host`` above); after a pod-wide death the restart point must be
# a commit that is VALID ON EVERY HOST — a step-N commit present on host
# 0 but torn or absent on host 1 would resume the pod from divergent
# state (or crash-loop one host).  The helpers below are the pure core:
# each host scans its own directory against the integrity manifests
# (file sha only — no unpickling, a torn pickle is never fed to the
# unpickler), the master intersects the reports, and each host rolls
# back to the agreed commit before respawning.
# ---------------------------------------------------------------------

def scan_commits(directory, prefix):
    """This prefix's committed checkpoints in ``directory``, validated
    against their manifest sidecars WITHOUT unpickling: ``{name:
    {"path", "mtime", "epoch", "incarnation", "process_index",
    "valid", "error"}}``.  ``valid`` is True (manifest's file sha
    matches), False (torn/corrupted, or unreadable), or None — a
    legacy commit with no manifest, which agreement treats as
    unverifiable (excluded) rather than trusted."""
    out = {}
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for name in names:
        if not name.startswith(prefix + "_") \
                or name.endswith("_current") \
                or name.endswith(MANIFEST_SUFFIX) \
                or name.endswith(".corrupt") or ".tmp" in name:
            continue
        path = os.path.join(directory, name)
        entry = {"path": path, "epoch": None, "incarnation": None,
                 "process_index": None, "topology": None,
                 "health": None, "valid": None, "error": None}
        try:
            entry["mtime"] = os.path.getmtime(path)
        except OSError:
            continue
        manifest = _load_manifest(path)
        if manifest is not None:
            entry["epoch"] = manifest.get("epoch")
            entry["incarnation"] = manifest.get("incarnation")
            entry["process_index"] = manifest.get("process_index")
            entry["topology"] = manifest.get("topology")
            entry["health"] = manifest.get("health")
            recorded = manifest.get("file_sha256")
            if recorded is None:
                entry["valid"] = None
                entry["error"] = "manifest without file sha"
            elif os.path.isdir(path):
                entry["valid"] = False
                entry["error"] = "directory checkpoint with a file sha"
            else:
                try:
                    entry["valid"] = _file_sha256(path) == recorded
                    if not entry["valid"]:
                        entry["error"] = "file sha mismatch (torn " \
                            "or corrupted commit)"
                except OSError as e:
                    entry["valid"] = False
                    entry["error"] = str(e)
        out[name] = entry
    return out


def _commit_order_key(name, per_host_entries):
    """Sort key for one commit name across hosts: epoch first (recorded
    in the manifest, SPMD-lockstep so identical everywhere), then the
    newest mtime any host saw — commit order is the same on every host,
    so any host's mtime ordering is the pod's."""
    epochs = [e.get("epoch") for e in per_host_entries
              if e.get("epoch") is not None]
    mtimes = [e.get("mtime", 0.0) for e in per_host_entries]
    return (max(epochs) if epochs else -1, max(mtimes), name)


def agree_commits(reports):
    """The pod's restart checkpoint by cross-host agreement.

    :param reports: ``{host: scan_commits(...)}`` — one report per
        host, each over that host's OWN directory.
    :returns: ``(agreed_name_or_None, detail)`` where detail maps every
        candidate name to ``{"hosts": [...], "valid_on": [...],
        "healthy": bool, "rejected": reason_or_None}`` — the newest
        name that is valid on EVERY host wins; a name absent or torn
        anywhere is rejected pod-wide (that is the point).  Commits
        stamped ``unhealthy:*`` by the numeric-fault sentinel on ANY
        host rank below every healthy candidate: a pod restarting
        after numerical death prefers the last commit whose sweep
        carried no anomaly, falling back to an unhealthy one only when
        nothing healthy survives (better a suspect checkpoint than
        none — the sentinel's own ladder bounds the replayed
        divergence)."""
    hosts = sorted(reports)
    names = set()
    for rep in reports.values():
        names.update(rep)
    detail = {}
    candidates = []
    for name in sorted(names):
        entries = [reports[h][name] for h in hosts if name in reports[h]]
        on = [h for h in hosts if name in reports[h]]
        valid_on = [h for h in hosts
                    if reports[h].get(name, {}).get("valid") is True]
        healthy = not any(
            str(e.get("health") or "").startswith("unhealthy")
            for e in entries)
        if len(on) < len(hosts):
            rejected = "absent on host(s) %s" % (
                [h for h in hosts if h not in on],)
        elif len(valid_on) < len(hosts):
            bad = [h for h in hosts if h not in valid_on]
            rejected = "invalid/unverified on host(s) %s" % (bad,)
        else:
            rejected = None
            candidates.append(
                ((1 if healthy else 0,)
                 + _commit_order_key(name, entries), name))
        detail[name] = {"hosts": on, "valid_on": valid_on,
                        "healthy": healthy, "rejected": rejected}
    if not candidates:
        return None, detail
    candidates.sort()
    return candidates[-1][1], detail


def rollback_to_commit(directory, prefix, agreed, quarantine=None,
                       scan=None):
    """Roll one host's checkpoint directory back to the agreed commit:
    quarantine every commit NEWER than it (valid here but not
    everywhere — resuming from it would diverge the pod) plus every
    invalid one, and point ``<prefix>_current`` at the agreed name so
    the respawned worker's ``--snapshot auto`` resumes from exactly the
    pod-agreed state.  ``agreed=None`` (no commit valid everywhere)
    quarantines everything — the pod starts fresh.  Returns the sorted
    list of quarantined names; best-effort on I/O errors (the respawn
    must proceed — ``--snapshot auto``'s own fallback covers leftovers).

    :param quarantine: when given (the pod master's explicit
        newer-than-agreed list, computed from the CROSS-host ordering),
        it replaces the local "newer" test — same-epoch commits break
        ties by mtime, and local clocks can disagree with the pod-wide
        key, so every host must quarantine the SAME set.  Locally
        invalid commits are quarantined either way.
    :param scan: an existing ``scan_commits(directory, prefix)`` report
        to reuse — the agent computed one for the agreement moments ago
        over the same quiesced ring, and rescanning would sha256 every
        checkpoint a second time on the restart path.
    """
    if scan is None:
        scan = scan_commits(directory, prefix)
    agreed_key = None
    if agreed is not None and agreed in scan:
        agreed_key = _commit_order_key(agreed, [scan[agreed]])
    quarantined = []
    for name, entry in scan.items():
        if name == agreed:
            continue
        if quarantine is not None:
            newer = name in quarantine
        else:
            newer = agreed_key is None or \
                _commit_order_key(name, [entry]) > agreed_key
        if newer or entry["valid"] is not True:
            if SnapshotterBase.quarantine(entry["path"]):
                quarantined.append(name)
    current = os.path.join(directory, "%s_current" % prefix)
    try:
        if os.path.islink(current) or os.path.exists(current):
            os.remove(current)
        if agreed is not None:
            os.symlink(agreed, current)
    except OSError:
        pass
    return sorted(quarantined)


class SnapshotterRegistry(UnitRegistry, MappedRegistry):
    """Name → snapshotter class (ref MappedUnitRegistry usage)."""


class SnapshotterBase(Unit, metaclass=SnapshotterRegistry):
    #: sharded backends whose save is itself a cross-process collective
    #: (every process writes its own shards) set this True
    all_processes_export = False
    #: class-level default for the commit-time poison valve so
    #: partially-constructed instances (tests build backends via
    #: ``__new__``) still carry the valve; ``__init__`` overrides it
    #: from config
    reject_nonfinite = True
    mapping = {}

    def __init__(self, workflow, **kwargs):
        super(SnapshotterBase, self).__init__(workflow, **kwargs)
        self.prefix = kwargs.get("prefix", workflow.name if workflow
                                 else "wf")
        self.interval = kwargs.get(
            "interval", root.common.snapshot.get("interval", 1))
        self.time_interval = kwargs.get(
            "time_interval",
            root.common.snapshot.get("min_interval_seconds", 0))
        self.directory = kwargs.get(
            "directory", root.common.dirs.get("snapshots", "snapshots"))
        self.compression = kwargs.get(
            "compression", root.common.snapshot.get("codec", "gz"))
        #: True → the pickle+compress+write happens on a background
        #: thread: the train loop only pays for the device→host gather
        #: (device_get), not the disk write — checkpointing a large model
        #: stops costing a step.  Writes are atomic (temp file + rename),
        #: ``destination`` is only set once the file is complete, and an
        #: atexit hook joins the in-flight write so process exit can
        #: never truncate a checkpoint.
        self.async_write = kwargs.get("async_write", False)
        #: crash-consistency knobs (docs/distributed_training.md
        #: "Preemption-safe training"): keep_last bounds the on-disk
        #: checkpoint ring (0 = unlimited); commit_retries/
        #: retry_backoff_ms retry the commit write on transient
        #: filesystem errors (NFS hiccups, EBUSY on shared storage)
        #: before surfacing; manifest=True writes a per-leaf checksum
        #: sidecar validated on restore, so a torn or bit-rotted
        #: checkpoint is DETECTED instead of silently resuming garbage.
        self.keep_last = int(kwargs.get(
            "keep_last", root.common.snapshot.get("keep_last", 5)))
        self.commit_retries = max(1, int(kwargs.get(
            "commit_retries",
            root.common.snapshot.get("commit_retries", 3))))
        self.retry_backoff = float(kwargs.get(
            "retry_backoff_ms",
            root.common.snapshot.get("retry_backoff_ms", 100))) / 1e3
        self.manifest = bool(kwargs.get(
            "manifest", root.common.snapshot.get("manifest", True)))
        #: commit-time poison valve (:class:`SnapshotNonFiniteError`):
        #: refuse to commit NaN/inf params/velocity so restart loops
        #: can never resume a poisoned state
        self.reject_nonfinite = bool(kwargs.get(
            "reject_nonfinite",
            root.common.snapshot.get("reject_nonfinite", True)))
        #: per-host export (the pod tier, services.podmaster): every
        #: process writes its own FULL checkpoint copy into its own
        #: (host-local) ``directory`` instead of only process 0 — the
        #: durability model for pods with host-local disks, and the
        #: substrate the pod master's cross-host checkpoint agreement
        #: runs over (a commit is only restartable if it is valid on
        #: ALL hosts).  Ignored on sharded backends whose save already
        #: is the collective (orbax writes one shared directory).
        self.per_host = bool(kwargs.get(
            "per_host", root.common.snapshot.get("per_host", False)))
        if self.per_host and self.all_processes_export:
            import logging
            logging.getLogger("Snapshotter").warning(
                "snapshot.per_host ignored: the %s backend already has "
                "every process writing (its save is the collective)",
                type(self).__name__)
            self.per_host = False
        #: optional run condition (a Bool or callable) checked INSIDE
        #: run() instead of via gate_skip: the unit must execute every
        #: cycle so the multi-host preemption agreement below runs
        #: unconditionally — gating it on any per-process condition
        #: (epoch_ended, a local preempt flag) would let one process
        #: enter the agreement collective while a peer skips the unit
        #: and dispatches the next training step: mismatched collectives,
        #: hung pod.  StandardWorkflow sets ``when = loader.epoch_ended``.
        self.when = kwargs.get("when")
        #: multi-host preemption agreement cadence: the allgather in
        #: ``_preempt_agreed`` is a blocking cross-host collective, and
        #: paying it every cycle is measurable on fast training loops.
        #: Cycle counts advance in lockstep across hosts (SPMD), so a
        #: modulo gate is deterministic — every process skips and runs
        #: the agreement on the same cycles, no divergent collectives.
        #: Worst case adds (N-1) cycles of latency before a preemption
        #: checkpoint, negligible against any real SIGTERM grace window.
        self.preempt_agree_every = int(
            kwargs.get("preempt_agree_every", 4)) or 1
        self._agree_cycle = 0
        self._preempt_latched = False
        self._writer = None
        if self.async_write:
            import atexit
            atexit.register(self.flush)
        self._epoch_counter = 0
        self._last_time = time.time()
        self.destination = None

    def collect(self):
        """Return the picklable state dict.  Override."""
        raise NotImplementedError

    def suffix(self):
        return "%d" % self._epoch_counter

    def _preempt_agreed(self, multihost):
        """Cross-host agreement on the workflow's preemption flag.  The
        scheduler's SIGTERMs race against unit boundaries, so one process
        can see the flag a cycle before another — and the snapshot path
        below runs collective gathers, where a divergent branch deadlocks.
        One tiny per-cycle allgather buys the agreement (single-host pays
        nothing)."""
        local = bool(getattr(self.workflow, "preempt_requested", False))
        if not multihost:
            return local
        import numpy as np
        from jax.experimental import multihost_utils
        return bool(multihost_utils.process_allgather(
            np.int32(local)).max())

    def run(self):
        multihost = jax.process_count() > 1
        # agreement FIRST, before any per-process gate — see the ``when``
        # comment in __init__.  Under multi-host the collective is
        # amortized to every N-th cycle (lockstep counter, so all hosts
        # agree on WHICH cycles run it); between agreement cycles the
        # local flag is ignored on every host alike, and a positive
        # agreement latches.  Single-host reads the local flag directly
        # every cycle — there is no collective to amortize.
        if not multihost:
            preempt = self._preempt_agreed(False)
        else:
            if not self._preempt_latched and \
                    self._agree_cycle % self.preempt_agree_every == 0:
                self._preempt_latched = self._preempt_agreed(True)
            self._agree_cycle += 1
            preempt = self._preempt_latched
        due = True
        if self.when is not None:
            due = bool(self.when() if callable(self.when) else self.when)
        if not due and not preempt:
            return
        if due:
            self._epoch_counter += 1
        if not preempt:
            if self.interval and self._epoch_counter % self.interval:
                return
            # the wall-clock gate is per-process and therefore NOT
            # deterministic across hosts — skipping it under multi-host
            # keeps every process taking the same branch into the
            # collective gathers below (a divergent decision would
            # deadlock allgather)
            if not multihost and \
                    time.time() - self._last_time < self.time_interval:
                return
        self._last_time = time.time()
        if multihost and jax.process_index() != 0 \
                and not self.all_processes_export and not self.per_host:
            # every process participates in the collective gathers inside
            # collect(), but only process 0 writes (ref
            # only-master-snapshots, snapshotter.py:160).  Sharded
            # backends (orbax) set ``all_processes_export``: their save
            # IS the collective — every process writes its own shards.
            # ``per_host`` instead has every process export a full copy
            # into its own host-local directory (the pod tier's
            # agreement substrate; collect() is symmetric either way).
            self.collect()
        else:
            self.export()
        if preempt:
            # never leave with a truncated checkpoint, then stop the
            # graph — the CLI exits 75 and the supervisor restart's
            # --snapshot auto resumes from this very file
            self.flush()
            self.info("preemption checkpoint complete — stopping")
            self.workflow.preempted_ = True
            self.workflow.stop()

    def export(self):
        os.makedirs(self.directory, exist_ok=True)
        fname = "%s_%s.pickle%s" % (self.prefix, self.suffix(),
                                    CODECS[self.compression][2])
        path = os.path.join(self.directory, fname)
        state = self.collect()          # device→host gather happens HERE
        self._check_finite(state)
        self._dispatch_write(self._write, state, fname, path)
        return path

    def _check_finite(self, state, trees=("params", "velocity")):
        """The ``reject_nonfinite`` poison valve (see
        :class:`SnapshotNonFiniteError`): float leaves of the model
        trees must be finite before any bytes hit storage."""
        if not self.reject_nonfinite or not isinstance(state, dict):
            return
        import numpy as np
        bad = []
        for key in trees:
            tree = state.get(key)
            if tree is None:
                continue
            for path, leaf in iter_state_leaves(tree, "/" + key):
                try:
                    a = np.asarray(leaf)
                except Exception:   # noqa: BLE001 — non-array leaf
                    continue
                if np.issubdtype(a.dtype, np.floating) and \
                        not np.isfinite(a).all():
                    bad.append(path)
        if bad:
            _surface_nonfinite(self.__dict__.get("prefix"), bad)
            raise SnapshotNonFiniteError(
                "refusing to commit a poisoned checkpoint: %d "
                "non-finite model leaf/leaves, first: %s — the last "
                "committed checkpoint stays finite; restart loops "
                "resume from it (root.common.snapshot."
                "reject_nonfinite=False disables this valve)"
                % (len(bad), ", ".join(bad[:5])))

    def _dispatch_write(self, write_fn, *args):
        """Run the (sync) write, or hand it to the single background
        writer thread under async_write — shared by the file and db
        backends so the async path cannot diverge."""
        if not self.async_write:
            write_fn(*args)
            return

        def logged():
            try:
                write_fn(*args)
            except Exception:   # noqa: BLE001 — must surface, not vanish
                self.exception("async snapshot write to %s failed"
                               % (args[-1],))   # path / db destination

        import threading
        self.flush()                    # one in-flight write at a time
        self._writer = threading.Thread(target=logged, daemon=True)
        self._writer.start()

    def _write(self, state, fname, path):
        opener, _, _ = CODECS[self.compression]
        # atomic: a crash mid-write leaves the previous snapshot intact
        # and _current never points at a partial file
        tmp = path + ".tmp"

        def commit():
            with opener(tmp) as f:
                pickle.dump(state, f, protocol=4)
            os.replace(tmp, path)

        self._commit_with_retries(commit, path)
        if self.manifest:
            # manifest AFTER the data rename, BEFORE the _current flip:
            # a checkpoint is only reachable once both exist, and a
            # crash between the two leaves a manifest-less (legacy-
            # validated) file that the next commit's flip supersedes
            manifest = state_manifest(state)
            manifest["file_sha256"] = _file_sha256(path)
            self._commit_with_retries(
                lambda: _write_json_atomic(path + MANIFEST_SUFFIX,
                                           manifest),
                path + MANIFEST_SUFFIX)
        self._flip_current(fname)
        self._prune_ring()
        self.destination = path   # only once the file is complete
        self.info("snapshot -> %s", path)
        self._flight_commit(path)

    def _commit_with_retries(self, fn, dest, exceptions=(OSError,)):
        """Run one commit step, retrying transient filesystem errors
        with exponential backoff — a shared-storage hiccup during a
        checkpoint must cost a retry, not the checkpoint."""
        delay = self.retry_backoff
        for attempt in range(1, self.commit_retries + 1):
            try:
                return fn()
            except exceptions as e:
                if attempt == self.commit_retries:
                    raise
                from veles_tpu.telemetry import flight
                flight.record("snapshot.retry", destination=dest,
                              attempt=attempt,
                              error="%s: %s" % (type(e).__name__, e))
                self.warning(
                    "transient error committing %s (attempt %d/%d): "
                    "%s — retrying in %.2fs", dest, attempt,
                    self.commit_retries, e, delay)
                time.sleep(delay)
                delay = min(delay * 2, 5.0)

    # ------------------------------------------------- keep-last-N ring
    def _ring_entries(self):
        """This prefix's committed checkpoints (data files/dirs only —
        no _current, manifests, quarantined .corrupt or .tmp leftovers),
        newest first by mtime."""
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return []
        out = []
        for n in names:
            if not n.startswith(self.prefix + "_") \
                    or n.endswith("_current") \
                    or n.endswith(MANIFEST_SUFFIX) \
                    or n.endswith(".corrupt") or ".tmp" in n:
                continue
            p = os.path.join(self.directory, n)
            try:
                out.append((os.path.getmtime(p), p))
            except OSError:
                continue
        out.sort(reverse=True)
        return [p for _, p in out]

    def _prune_ring(self):
        """Bound the on-disk checkpoint ring to the newest keep_last
        commits (plus whatever _current points at — the resume anchor
        is never collected, even if mtimes lie).  Best-effort: pruning
        failures must never fail the commit that triggered them."""
        if self.keep_last <= 0:
            return
        current = os.path.join(self.directory,
                               "%s_current" % self.prefix)
        try:
            anchor = os.path.realpath(current) \
                if os.path.islink(current) else None
        except OSError:
            anchor = None
        for path in self._ring_entries()[self.keep_last:]:
            if anchor and os.path.realpath(path) == anchor:
                continue
            try:
                self._remove_checkpoint(path)
                # info, not debug: the ring DELETES data — retention
                # must be visible in every training log (keep_last=0
                # disables the ring entirely)
                self.info("pruned old checkpoint %s (keep_last=%d)",
                          path, self.keep_last)
            except OSError:
                pass

    @staticmethod
    def _remove_checkpoint(path):
        if os.path.isdir(path):
            import shutil
            shutil.rmtree(path)
        else:
            os.remove(path)
        manifest = path + MANIFEST_SUFFIX
        if os.path.exists(manifest):
            os.remove(manifest)

    @staticmethod
    def quarantine(path):
        """Rename a checkpoint that failed to load/validate to
        ``<name>.corrupt`` (manifest rides along) so restart loops stop
        re-trying it and ring pruning/fallback scans skip it.  Returns
        the new path, or None when the rename was impossible."""
        real = os.path.realpath(path)
        try:
            target = real + ".corrupt"
            os.replace(real, target)
            if os.path.exists(real + MANIFEST_SUFFIX):
                os.replace(real + MANIFEST_SUFFIX,
                           target + MANIFEST_SUFFIX)
            return target
        except OSError:
            return None

    def _flight_commit(self, destination):
        """Snapshot commits join the flight record: in a post-mortem the
        distance between the last commit and the crash IS the work
        lost (never raises — shared by all backends; __dict__ reads so
        a partially constructed unit can still export)."""
        from veles_tpu.telemetry import flight
        meta = commit_meta()
        flight.record("snapshot",
                      unit=self.__dict__.get("name"),
                      destination=destination,
                      epoch=self.__dict__.get("_epoch_counter"),
                      process_index=meta.get("process_index"),
                      incarnation=meta.get("incarnation"))

    def _flip_current(self, fname):
        """Point ``<prefix>_current`` at a COMPLETED checkpoint — the
        resume-critical symlink shared by every backend (ref the
        _current symlink, snapshotter.py:397-409)."""
        current = os.path.join(self.directory, "%s_current" % self.prefix)
        try:
            if os.path.islink(current) or os.path.exists(current):
                os.remove(current)
            os.symlink(fname, current)
        except OSError:
            pass

    def flush(self):
        """Join the in-flight async write (call before reading the
        snapshot back or at shutdown)."""
        if self._writer is not None:
            self._writer.join()
            self._writer = None

    @staticmethod
    def import_(path, allow_remote=False, expected_sha256=None):
        """Load a snapshot dict from a file or an http(s) URL (ref
        SnapshotterToFile.import_ snapshotter.py:412 and the http import
        path __main__.py:539-589; follows the _current symlink).

        Snapshots are pickles — loading one executes code.  Remote URLs
        therefore require an explicit opt-in: ``allow_remote=True`` (CLI
        ``--allow-remote-snapshot``) or ``VELES_ALLOW_REMOTE_SNAPSHOT=1``.
        ``expected_sha256`` is verified (local or remote) before any
        unpickling."""
        import hashlib
        tmp_path = None
        if path.startswith(("http://", "https://")):
            import logging
            import tempfile
            import urllib.request
            if not (allow_remote
                    or os.environ.get("VELES_ALLOW_REMOTE_SNAPSHOT") == "1"):
                raise PermissionError(
                    "remote snapshot import from %s refused: pickle import "
                    "runs code.  Pass --allow-remote-snapshot (or set "
                    "VELES_ALLOW_REMOTE_SNAPSHOT=1) to opt in." % path)
            logging.getLogger("Snapshotter").warning(
                "loading remote snapshot %s — pickle import runs code; "
                "only use trusted%s hosts", path,
                "" if path.startswith("https://") else " (and https)")
            base = os.path.basename(path.split("?", 1)[0])
            suffix = base[base.find("."):] if "." in base else ".pickle"
            tmp = tempfile.NamedTemporaryFile(suffix=suffix, delete=False)
            tmp_path = tmp.name
        try:
            if tmp_path is not None:
                with urllib.request.urlopen(path) as resp, tmp:
                    while True:
                        chunk = resp.read(1 << 20)
                        if not chunk:
                            break
                        tmp.write(chunk)
                path = tmp_path
            if expected_sha256 is not None:
                h = hashlib.sha256()
                with open(os.path.realpath(path), "rb") as f:
                    for chunk in iter(lambda: f.read(1 << 20), b""):
                        h.update(chunk)
                digest = h.hexdigest()
                if digest != expected_sha256.lower():
                    raise ValueError(
                        "snapshot sha256 mismatch: got %s, expected %s"
                        % (digest, expected_sha256.lower()))
            return SnapshotterBase._import_file(path)
        finally:
            if tmp_path is not None:
                os.unlink(tmp_path)

    @staticmethod
    def _import_file(path):
        real = os.path.realpath(path)
        if os.path.isdir(real) and \
                os.path.exists(os.path.join(real, "state.pickle")):
            # an .orbax checkpoint DIRECTORY (sharded backend)
            return OrbaxSnapshotter.import_dir(real)
        manifest = _load_manifest(real)
        file_verified = False
        if manifest is not None and "file_sha256" in manifest:
            # file digest BEFORE any unpickling: a pickle import runs
            # code, so a torn/corrupted checkpoint must be rejected
            # without ever feeding its bytes to the unpickler
            digest = _file_sha256(real)
            if digest != manifest["file_sha256"]:
                raise SnapshotIntegrityError(
                    "checkpoint %s failed its integrity manifest: file "
                    "sha256 %s != recorded %s (torn or corrupted "
                    "commit)" % (real, digest[:16],
                                 manifest["file_sha256"][:16]))
            file_verified = True
        state = None
        for codec, (_, opener, ext) in CODECS.items():
            if real.endswith(".pickle" + ext) and (ext or
                                                   real.endswith(".pickle")):
                with opener(real) as f:
                    state = pickle.load(f)
                break
        if state is None:
            with open(real, "rb") as f:   # best effort: plain pickle
                state = pickle.load(f)
        if manifest is not None and not file_verified:
            # leaf-level validation only when the cheaper whole-file
            # digest was unavailable (legacy manifest): the leaf
            # digests were derived from exactly the bytes the file
            # hash just covered, so re-hashing every array would
            # double resume-time hashing for nothing
            validate_state_manifest(state, manifest, source=real)
        return state

    def get_metric_values(self):
        return {"snapshot": self.destination}


class TrainingSnapshotter(SnapshotterBase):
    """Checkpoints a StandardWorkflow-style training run."""

    MAPPING = "file"

    def __init__(self, workflow, **kwargs):
        super(TrainingSnapshotter, self).__init__(workflow, **kwargs)
        self.demand("trainer", "loader")
        self.decision = None

    def collect(self):
        # drain queued fused-dispatch steps FIRST: with
        # steps_per_dispatch > 1 the loader offset already covers the
        # queued minibatches, so params gathered without a flush would
        # lag the recorded position — an inexact (silently wrong) resume
        self.trainer.flush()
        state = {
            "params": self.trainer.host_params(),
            "velocity": self.trainer.host_velocity(),
            "loader": self.loader.state,
            "prng": prng.states(),
            "epoch": self.loader.epoch_number,
            # per-step RNG position: without it a resumed run would replay
            # already-consumed dropout/stochastic-pooling keys
            "step_counter": self.trainer._step_counter,
            # mid-sweep class-stat accumulators: a preemption checkpoint
            # lands at a cycle boundary INSIDE an epoch, and without
            # these the resumed epoch's stats would only cover the
            # post-resume minibatches — the decision's metric for that
            # epoch would diverge from an uninterrupted run
            "trainer_stats": jax.device_get(self.trainer.class_stats),
            # the mesh the commit is written under — reshard_state
            # proves (or refuses) a cross-topology resume against it
            "topology": mesh_topology(
                getattr(self.trainer, "mesh_config", None)),
        }
        verdict = getattr(self.trainer, "health_verdict", None)
        if callable(verdict):
            # the sentinel's health stamp: "healthy" when no numeric
            # anomaly landed since the previous commit, else
            # "unhealthy:<kind>" — rides commit_meta into the manifest
            # so rollback/agreement read it without unpickling
            health = verdict()
            if health is not None:
                state["health"] = health
        if self.decision is not None:
            state["decision"] = {
                "best_metric": self.decision.best_metric,
                "best_epoch": self.decision.best_epoch,
                "epochs_since_improvement":
                    self.decision.epochs_since_improvement,
                # class sweeps already read this epoch (test/valid done,
                # train in flight) — same mid-sweep exactness story
                "epoch_metrics": list(self.decision.epoch_metrics),
            }
        return state

    def suffix(self):
        if self.decision is not None and \
                self.decision.best_metric is not None:
            return "%d_%.4f" % (self.loader.epoch_number,
                                self.decision.best_metric)
        return "%d" % self.loader.epoch_number

    @staticmethod
    def restore(workflow, snapshot):
        """Apply a snapshot dict to an initialized workflow — training
        continues mid-stream (ref §3.5 resume).  A checkpoint written
        under a different mesh topology first passes
        :func:`reshard_state`: the resize is proven deterministic (or
        refused) BEFORE any state is applied, and the cross-topology
        resume joins the flight record."""
        trainer, loader = workflow.trainer, workflow.loader
        live = mesh_topology(getattr(trainer, "mesh_config", None))
        snapshot, reshard = reshard_state(
            snapshot, live,
            minibatch_size=getattr(loader, "minibatch_size", None))
        if reshard["changed"]:
            from veles_tpu.telemetry import flight
            flight.record("snapshot.reshard",
                          source=reshard["from"], target=reshard["to"],
                          checks=reshard["checks"])
            import logging
            logging.getLogger("Snapshotter").info(
                "resharding checkpoint written under %s onto %s (%s)",
                reshard["from"], reshard["to"],
                "; ".join(reshard["checks"]))
        trainer.load_params(snapshot["params"], snapshot.get("velocity"))
        trainer._step_counter = snapshot.get("step_counter", 0)
        loader.state = snapshot["loader"]
        prng.restore_states(snapshot["prng"])
        if "trainer_stats" in snapshot:
            # mid-sweep accumulators (see collect).  Under a mesh the
            # accumulators are REPLICATED scalars (_shard_pins), so the
            # restore re-places them explicitly with the same sharding —
            # multi-process safe via make_array_from_callback (every
            # host restores the identical checkpointed value, so the
            # replicas agree by construction).  This is what keeps a
            # pod's graceful mid-epoch preemption bit-exact: without it
            # a sharded resume would restart the interrupted sweep's
            # stats and the epoch's decision metrics would diverge from
            # an uninterrupted run.
            import jax.numpy as jnp
            mc = getattr(trainer, "mesh_config", None)
            if mc is None:
                place = jnp.asarray
            else:
                import numpy as np

                from veles_tpu.parallel import sharding
                repl = sharding.replicated_sharding(mc)

                def place(v, _repl=repl):
                    a = np.asarray(v)
                    return jax.make_array_from_callback(
                        a.shape, _repl, lambda idx: a[idx])
            trainer.class_stats = [
                jax.tree_util.tree_map(place, s)
                for s in snapshot["trainer_stats"]]
        dec = getattr(workflow, "decision", None)
        if dec is not None and "decision" in snapshot:
            d = snapshot["decision"]
            dec.best_metric = d["best_metric"]
            dec.best_epoch = d["best_epoch"]
            dec.epochs_since_improvement = d["epochs_since_improvement"]
            if "epoch_metrics" in d:
                dec.epoch_metrics = list(d["epoch_metrics"])

    @staticmethod
    def warm_start(workflow, snapshot):
        """Fine-tuning initializer (CLI ``--warm-start``): copy over
        every snapshot param whose layer name, param name AND shape
        match the freshly built model; everything else — mismatched or
        new layers, optimizer moments, loader position, PRNG, decision
        state — stays fresh.  The exact-resume path is ``restore``;
        this one deliberately tolerates architecture changes (swap the
        head, widen a layer, add blocks) and reports what it took.

        :returns: (n_restored, n_skipped) leaf counts."""
        import logging
        import numpy as np

        log = logging.getLogger("Snapshotter")
        from veles_tpu.services.export import (_flatten_params,
                                               unflatten_params)
        trainer = workflow.trainer
        live = trainer.host_params()
        merged = {}
        restored = skipped = 0
        snap_params = snapshot["params"]
        for lname, sub in live.items():
            src = snap_params.get(lname)
            # leaf-wise over "/"-joined names so NESTED trees
            # (transformer blocks' mha/ln subtrees, residual composites,
            # LoRA adapters) warm-start per leaf — a lora model
            # warm-started from a base snapshot restores every base
            # matrix and keeps its fresh adapters
            flat_live = _flatten_params(sub)
            flat_src = {} if src is None else _flatten_params(src)
            out = {}
            for pname, arr in flat_live.items():
                cand = flat_src.get(pname)
                if cand is not None and \
                        np.shape(cand) == np.shape(arr):
                    # cast to the LIVE dtype: an f32 snapshot must not
                    # plant f32 leaves into a bf16-master-params tree
                    # (mixed-dtype donation/retrace errors)
                    out[pname] = np.asarray(cand).astype(
                        np.asarray(arr).dtype)
                    restored += 1
                else:
                    out[pname] = arr
                    skipped += 1
                    if cand is not None:
                        log.warning(
                            "warm-start: %s/%s shape %s != snapshot %s "
                            "— keeping fresh init", lname, pname,
                            np.shape(arr), np.shape(cand))
            merged[lname] = unflatten_params(out)
        dropped = sorted(set(snap_params) - set(live))
        if dropped:
            log.info("warm-start: snapshot layers not in this model: %s",
                     ", ".join(dropped))
        trainer.load_params(merged)       # moments/loader/PRNG stay fresh
        if getattr(trainer, "ema_decay", None) and \
                "ema" in getattr(trainer, "velocity", {}):
            # the EMA average was seeded from the DISCARDED random init;
            # reseed from the warm-started params or use_ema would
            # serve near-random weights
            import jax
            import jax.numpy as jnp
            trainer.velocity["ema"] = jax.tree_util.tree_map(
                lambda p: jnp.array(p, jnp.float32), trainer.params)
        log.info("warm-start: restored %d param leaves, kept %d fresh",
                 restored, skipped)
        return restored, skipped


class DBSnapshotter(TrainingSnapshotter):
    """Database-backed snapshotter (ref SnapshotterToDB,
    snapshotter.py:428-518 — the reference used ODBC; sqlite is the
    zero-dependency stand-in, same capability: checkpoints addressable by
    query instead of filesystem paths)."""

    MAPPING = "db"

    def __init__(self, workflow, dsn="snapshots.sqlite", **kwargs):
        super(DBSnapshotter, self).__init__(workflow, **kwargs)
        self.dsn = dsn

    def _connect(self):
        import sqlite3
        conn = sqlite3.connect(self.dsn)
        conn.execute(
            "CREATE TABLE IF NOT EXISTS snapshots ("
            " id INTEGER PRIMARY KEY AUTOINCREMENT,"
            " prefix TEXT, suffix TEXT, created REAL, state BLOB,"
            " sha256 TEXT, meta TEXT)")
        for clause in ("sha256 TEXT", "meta TEXT"):
            try:  # pre-integrity / pre-provenance databases: widen
                conn.execute("ALTER TABLE snapshots ADD COLUMN "
                             + clause)
            except sqlite3.OperationalError:
                pass  # already has the column
        return conn

    def export(self):
        state = self.collect()          # device→host gather on the loop
        self._check_finite(state)
        suffix = self.suffix()
        dest = "%s#%s_%s" % (self.dsn, self.prefix, suffix)
        self._dispatch_write(self._db_write, state, suffix, dest)
        return dest

    def _db_write(self, state, suffix, dest):
        import sqlite3
        blob = pickle.dumps(state, protocol=4)
        digest = hashlib.sha256(blob).hexdigest()

        meta = json.dumps(commit_meta(state))

        def commit():
            conn = self._connect()
            try:
                with conn:
                    conn.execute(
                        "INSERT INTO snapshots"
                        " (prefix, suffix, created, state, sha256, meta)"
                        " VALUES (?, ?, ?, ?, ?, ?)",
                        (self.prefix, suffix, time.time(), blob, digest,
                         meta))
                    if self.keep_last > 0:
                        # the ring, in-transaction: the insert and the
                        # prune commit (or roll back) together
                        conn.execute(
                            "DELETE FROM snapshots WHERE prefix = ? AND"
                            " id NOT IN (SELECT id FROM snapshots WHERE"
                            " prefix = ? ORDER BY id DESC LIMIT ?)",
                            (self.prefix, self.prefix, self.keep_last))
            finally:
                conn.close()

        self._commit_with_retries(
            commit, dest, exceptions=(OSError, sqlite3.OperationalError))
        self.destination = dest   # only once the row is committed
        self.info("snapshot -> %s", dest)
        self._flight_commit(dest)

    @staticmethod
    def import_db(dsn, prefix=None):
        """Load the most recent VALID snapshot (optionally for one
        prefix): a row whose blob fails its recorded sha256 — a torn
        write the sqlite journal could not cover, or bit rot — is
        skipped with a warning and the previous row is tried, the
        db-backend twin of the file fallback."""
        import logging
        import sqlite3
        conn = sqlite3.connect(dsn)
        try:
            q = "SELECT id, state, sha256 FROM snapshots"
            args = ()
            if prefix is not None:
                q += " WHERE prefix = ?"
                args = (prefix,)
            q += " ORDER BY id DESC"
            # iterate the cursor: only one blob resident at a time (a
            # ring of multi-GB checkpoints must not all materialize
            # just to validate the newest row)
            seen = False
            for row_id, blob, digest in conn.execute(q, args):
                seen = True
                if digest is not None and \
                        hashlib.sha256(blob).hexdigest() != digest:
                    logging.getLogger("Snapshotter").warning(
                        "snapshot row %d in %s failed its sha256 — "
                        "torn or corrupted; trying the previous row",
                        row_id, dsn)
                    continue
                return pickle.loads(blob)
        finally:
            conn.close()
        if not seen:
            raise KeyError("no snapshot in %s (prefix=%r)" % (dsn, prefix))
        raise SnapshotIntegrityError(
            "every snapshot row in %s (prefix=%r) failed its sha256"
            % (dsn, prefix))


class OrbaxSnapshotter(TrainingSnapshotter):
    """Sharded checkpointing via orbax — SURVEY §5's own prescription
    for the TPU equivalent of the reference's whole-graph pickle
    ("orbax-style checkpoint of (params, opt state, loader state, PRNG
    key) + config-addressed topology").

    Unlike the pickle backends, the array trees are saved AS THE LIVE
    ``jax.Array``s: no device→host gather, and under multi-host SPMD
    ``save`` is itself the collective — every process writes exactly
    its own shards into one checkpoint directory on shared storage, so
    checkpoint cost scales with the PER-HOST shard bytes, not the model
    size.  The non-array sidecar (loader position, named-PRNG streams,
    epoch, decision bookkeeping) is a small pickle inside the same
    directory, written by process 0.

    Select with ``snapshotter_config={"name": "orbax", ...}``.  The
    checkpoint is a DIRECTORY ``<prefix>_<suffix>.orbax/`` with the
    usual ``_current`` symlink; ``SnapshotterBase.import_`` detects the
    layout, so ``--snapshot auto`` resume works unchanged.
    ``async_write=True`` rides orbax's AsyncCheckpointer (the barrier
    is ``flush()``, same contract as the pickle writer thread)."""

    MAPPING = "orbax"
    all_processes_export = True

    def __init__(self, workflow, finalize_timeout=120.0, **kwargs):
        super(OrbaxSnapshotter, self).__init__(workflow, **kwargs)
        self._ckptr = None
        #: (name, path) of an async commit whose _current flip awaits
        #: the arrays finalize — see flush()
        self._pending = None
        #: seconds to wait for orbax's background commit before the
        #: _current flip gives up (multi-GB checkpoints on slow shared
        #: storage need more than the old 30 s)
        self.finalize_timeout = float(finalize_timeout)
        self._finalize_failures = 0

    def _checkpointer(self):
        import orbax.checkpoint as ocp
        if self._ckptr is None:
            if self.async_write:
                self._ckptr = ocp.AsyncCheckpointer(
                    ocp.StandardCheckpointHandler())
            else:
                self._ckptr = ocp.StandardCheckpointer()
            # orbax keeps commit threads; close BEFORE interpreter
            # teardown or a pending finalize raises "cannot schedule
            # new futures after interpreter shutdown"
            import atexit
            atexit.register(self._close)
        return self._ckptr

    def _close(self):
        if self._ckptr is not None:
            try:
                # finalize any in-flight async commit (atexit runs this
                # BEFORE the base flush hook — LIFO), then release
                self.flush()
                self._ckptr.close()
            except Exception:   # noqa: BLE001 — shutdown best-effort
                pass
            self._ckptr = None

    def stop(self):
        self._close()
        super(OrbaxSnapshotter, self).stop()

    def collect(self):
        # the point of this backend: do NOT gather to host.  Build the
        # non-array sidecar fields directly and keep the LIVE device
        # arrays — orbax serializes shard-by-shard, each process writing
        # only what it addresses.  (TrainingSnapshotter.collect would
        # pay the full host_params/host_velocity gather only for the
        # trees to be thrown away.)
        t = self.trainer
        t.flush()                       # drain pending fused steps
        state = {
            "params": t.params,
            "velocity": t.velocity,
            "loader": self.loader.state,
            "prng": prng.states(),
            "epoch": self.loader.epoch_number,
            "step_counter": t._step_counter,
            # mid-sweep accumulators (see TrainingSnapshotter.collect);
            # a handful of scalars — the no-gather contract is about
            # the param/velocity trees
            "trainer_stats": jax.device_get(t.class_stats),
            "topology": mesh_topology(
                getattr(t, "mesh_config", None)),
        }
        if self.decision is not None:
            state["decision"] = {
                "best_metric": self.decision.best_metric,
                "best_epoch": self.decision.best_epoch,
                "epochs_since_improvement":
                    self.decision.epochs_since_improvement,
                "epoch_metrics": list(self.decision.epoch_metrics),
            }
        verdict = getattr(t, "health_verdict", None)
        if callable(verdict):
            # sentinel health stamp (rides the pickle sidecar +
            # manifest.json, same contract as the file backend)
            health = verdict()
            if health is not None:
                state["health"] = health
        return state

    def export(self):
        os.makedirs(self.directory, exist_ok=True)
        name = "%s_%s.orbax" % (self.prefix, self.suffix())
        path = os.path.abspath(os.path.join(self.directory, name))
        state = self.collect()
        arrays = {"params": state.pop("params"),
                  "velocity": state.pop("velocity")}
        if self.reject_nonfinite:
            # device-side reduction (no gather — the backend's point):
            # one scalar per leaf, synced with the sidecar write anyway
            import jax.numpy as jnp
            bad = [p for p, v in iter_state_leaves(arrays)
                   if jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating)
                   and not bool(jnp.isfinite(v).all())]
            if bad:
                _surface_nonfinite(self.__dict__.get("prefix"), bad)
                raise SnapshotNonFiniteError(
                    "refusing to commit a poisoned checkpoint: "
                    "non-finite model leaves %s" % bad[:5])
        self.flush()                    # one in-flight commit at a time
        os.makedirs(path, exist_ok=True)
        if jax.process_index() == 0:
            # sidecar BEFORE the commit: the checkpoint only becomes
            # reachable when _current flips, and the flip waits for the
            # arrays finalize — a crash mid-commit leaves _current on
            # the previous good checkpoint (the base-class atomicity
            # contract)
            with open(os.path.join(path, "state.pickle"), "wb") as f:
                pickle.dump(state, f, protocol=4)
            if self.manifest:
                # integrity sidecar: per-leaf checksums for the pickle
                # sidecar, STRUCTURE (paths/shapes/dtypes) for the
                # array trees — checksumming the arrays would force the
                # device→host gather this backend exists to avoid;
                # torn array writes are orbax's own finalization gate
                man = state_manifest(state)
                man["arrays"] = {
                    p: {"shape": list(v.shape), "dtype": str(v.dtype)}
                    for p, v in iter_state_leaves(arrays)}
                _write_json_atomic(
                    os.path.join(path, "manifest.json"), man)
        ckptr = self._checkpointer()
        # orbax finalizes arrays/ atomically (tmp dir + rename) and,
        # under multi-host, coordinates the commit across processes
        ckptr.save(os.path.join(path, "arrays"), arrays, force=True)
        if self.async_write:
            self._pending = (name, path)
            self.info("snapshot -> %s (async, committing)", path)
        else:
            self._finalize(name, path)
        return path

    def _finalize(self, name, path):
        # orbax commits from a background executor EVEN on the "sync"
        # Checkpointer (measured on 0.11.32: save() returns before the
        # tmp-dir rename), so a crash in that window leaves _current
        # pointing at a directory restore cannot load.  Gate the flip
        # on PUBLIC APIs only — no private marker filenames that a
        # future orbax may rename (ADVICE r4):
        #   1. AsyncCheckpointer.wait_until_finished() drains the
        #      commit executor when available;
        #   2. poll until ckptr.metadata(arrays) succeeds AND
        #      ocp.utils.is_checkpoint_finalized passes — metadata()
        #      requires the finalized directory + readable tree
        #      metadata, exactly what restore needs (verified on
        #      0.11.32: once metadata() succeeds, restore succeeds;
        #      is_checkpoint_finalized alone is necessary but NOT
        #      sufficient — it only checks tmp-naming).
        import orbax.checkpoint as ocp
        ckptr = self._checkpointer()
        if hasattr(ckptr, "wait_until_finished"):
            ckptr.wait_until_finished()
        arrays = os.path.join(path, "arrays")
        deadline = time.time() + self.finalize_timeout
        while time.time() < deadline:
            try:
                if ocp.utils.is_checkpoint_finalized(arrays):
                    ckptr.metadata(arrays)
                    break
            except Exception:  # noqa: BLE001 — not committed/visible yet
                pass
            time.sleep(0.05)
        else:
            # a silently stale _current would make supervisor restarts
            # resume from ever-older checkpoints while training looks
            # healthy — fail loudly instead (the previous good
            # checkpoint stays reachable either way)
            raise RuntimeError(
                "orbax checkpoint %s never finalized — _current still "
                "points at the previous snapshot" % path)
        if jax.process_index() == 0:
            self._flip_current(name)
            self._prune_ring()
        self.destination = path   # only once the commit is final
        self.info("snapshot -> %s", path)
        self._flight_commit(path)

    def flush(self):
        if self._ckptr is not None and self.async_write:
            self._ckptr.wait_until_finished()
        if self._pending is not None:
            name, path = self._pending
            self._pending = None
            try:
                self._finalize(name, path)
                self._finalize_failures = 0
            except Exception:
                # keep the flip pending ONCE: a commit that merely
                # outlived the timeout retries at the next flush.  A
                # second failure abandons it — export() flushes before
                # every save, so a permanently-torn commit must not
                # wedge every future checkpoint behind its timeout.
                self._finalize_failures += 1
                if self._finalize_failures < 2:
                    self._pending = (name, path)
                else:
                    self.error("abandoning unfinalizable checkpoint %s "
                               "after %d attempts — _current stays on "
                               "the previous snapshot; future exports "
                               "proceed", path, self._finalize_failures)
                    self._finalize_failures = 0
                raise

    @staticmethod
    def import_dir(path):
        """Load an .orbax checkpoint directory back into the standard
        snapshot dict.  Arrays restore as HOST numpy regardless of the
        saving topology (a checkpoint written by an 8-process pod must
        import on a 1-process dev box); restore/load_params re-place
        them under whatever mesh the live workflow runs."""
        import numpy as np

        import orbax.checkpoint as ocp
        arrays_path = os.path.join(path, "arrays")
        ckptr = ocp.PyTreeCheckpointer()
        # metadata() API drift across pinned orbax versions: 0.7.x
        # returns the bare metadata TREE (a dict of ArrayMetadata),
        # later versions wrap it (.item_metadata, sometimes again in
        # .tree) — unwrap whatever is there down to the tree
        meta = ckptr.metadata(arrays_path)
        tree = getattr(meta, "item_metadata", meta)
        tree = getattr(tree, "tree", tree)
        restore_args = jax.tree_util.tree_map(
            lambda m: ocp.RestoreArgs(restore_type=np.ndarray), tree)
        arrays = ckptr.restore(
            arrays_path, args=ocp.args.PyTreeRestore(
                restore_args=restore_args))
        ckptr.close()
        with open(os.path.join(path, "state.pickle"), "rb") as f:
            state = pickle.load(f)
        manifest = None
        try:
            with open(os.path.join(path, "manifest.json")) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            pass                      # legacy checkpoint: unvalidated
        if manifest is not None:
            validate_state_manifest(
                state, manifest,
                source=os.path.join(path, "state.pickle"))
            recorded = manifest.get("arrays", {})
            live = {p: {"shape": list(np.shape(v)),
                        "dtype": str(np.asarray(v).dtype)}
                    for p, v in iter_state_leaves(arrays)}
            if recorded and recorded != live:
                bad = [p for p in sorted(set(recorded) | set(live))
                       if recorded.get(p) != live.get(p)]
                raise SnapshotIntegrityError(
                    "%s failed its array-structure manifest: %s"
                    % (path, ", ".join(bad[:5])))
        state.update(arrays)
        return state
