"""Plotting units (ref: veles/plotter.py, plotting_units.py:52-822,
graphics_server.py/graphics_client.py).

The reference shipped pickled Plotter objects over ZMQ pub/sub to an
out-of-process matplotlib client.  Here plotters render headlessly (Agg)
to PNG files in an output directory and push their payload dicts to an
in-process ``PlotBus`` that the web-status dashboard serves — same
decoupling (compute loop never blocks on rendering), no subprocess.

Plotter library parity: accumulating (metric-vs-epoch curves), matrix
(confusion), image (weights/samples), histogram."""

import os
import threading

import numpy as np

from veles_tpu.units import Unit


class PlotBus(object):
    """In-process pub/sub of plot payloads (ref GraphicsServer ZMQ PUB).
    ``subscribe(fn)`` fans payloads out to live listeners (the ZMQ
    graphics server bridges them to other processes — services.graphics).
    """

    def __init__(self, capacity=256):
        self._items = []
        self._capacity = capacity
        self._lock = threading.Lock()
        self._subscribers = []

    def publish(self, payload):
        with self._lock:
            self._items.append(payload)
            if len(self._items) > self._capacity:
                del self._items[:self._capacity // 2]
            subscribers = list(self._subscribers)
        for fn in subscribers:
            fn(payload)

    def subscribe(self, fn):
        with self._lock:
            self._subscribers.append(fn)

    def unsubscribe(self, fn):
        with self._lock:
            if fn in self._subscribers:
                self._subscribers.remove(fn)

    def snapshot(self):
        with self._lock:
            return list(self._items)


bus = PlotBus()


def _matplotlib():
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    return plt


class PlotterBase(Unit):
    """Renders every ``redraw_interval`` runs (ref Plotter redraw throttle,
    plotter.py:147-158)."""

    def __init__(self, workflow, name=None, directory=None,
                 redraw_interval=1, **kwargs):
        super(PlotterBase, self).__init__(workflow, name=name or
                                          type(self).__name__, **kwargs)
        self.directory = directory or "plots"
        self.redraw_interval = redraw_interval
        self._runs = 0
        self.last_file = None
        self.view_group = "PLOTTER"

    def run(self):
        self._runs += 1
        if self._runs % self.redraw_interval:
            return
        payload = self.payload()
        if payload is None:
            return
        bus.publish({"name": self.name, **payload})
        os.makedirs(self.directory, exist_ok=True)
        self.last_file = os.path.join(self.directory,
                                      "%s.png" % self.name)
        self.render(payload, self.last_file)

    def payload(self):
        """Return the JSON-able data dict to publish, or None to skip."""
        raise NotImplementedError

    def render(self, payload, path):
        raise NotImplementedError


class AccumulatingPlotter(PlotterBase):
    """Curve of a scalar metric over epochs (ref plotting_units
    AccumulatingPlotter).  Set ``source=callable`` returning the value."""

    def __init__(self, workflow, source=None, ylabel="value", **kwargs):
        super(AccumulatingPlotter, self).__init__(workflow, **kwargs)
        self.source = source
        self.ylabel = ylabel
        self.values = []

    def payload(self):
        v = self.source() if callable(self.source) else self.source
        if v is None:
            return None
        self.values.append(float(v))
        return {"kind": "curve", "values": list(self.values),
                "ylabel": self.ylabel}

    def render(self, payload, path):
        plt = _matplotlib()
        fig, ax = plt.subplots(figsize=(6, 4))
        ax.plot(payload["values"], marker="o", markersize=3)
        ax.set_xlabel("epoch")
        ax.set_ylabel(payload["ylabel"])
        ax.grid(True, alpha=0.3)
        fig.savefig(path, dpi=80)
        plt.close(fig)


class MatrixPlotter(PlotterBase):
    """Confusion-matrix heatmap (ref MatrixPlotter)."""

    def __init__(self, workflow, source=None, **kwargs):
        super(MatrixPlotter, self).__init__(workflow, **kwargs)
        self.source = source

    def payload(self):
        m = self.source() if callable(self.source) else self.source
        if m is None:
            return None
        return {"kind": "matrix", "matrix": np.asarray(m).tolist()}

    def render(self, payload, path):
        plt = _matplotlib()
        m = np.asarray(payload["matrix"])
        fig, ax = plt.subplots(figsize=(5, 5))
        im = ax.imshow(m, cmap="viridis")
        fig.colorbar(im)
        ax.set_xlabel("predicted")
        ax.set_ylabel("true")
        fig.savefig(path, dpi=80)
        plt.close(fig)


class ImagePlotter(PlotterBase):
    """Grid of images — e.g. first-layer weights (ref Weights2D/ImagePlotter)."""

    def __init__(self, workflow, source=None, grid_shape=None, **kwargs):
        super(ImagePlotter, self).__init__(workflow, **kwargs)
        self.source = source
        self.grid_shape = grid_shape

    def payload(self):
        imgs = self.source() if callable(self.source) else self.source
        if imgs is None:
            return None
        return {"kind": "images", "images": np.asarray(imgs).tolist()}

    def render(self, payload, path):
        plt = _matplotlib()
        imgs = np.asarray(payload["images"])
        n = len(imgs)
        cols = self.grid_shape[1] if self.grid_shape else \
            int(np.ceil(np.sqrt(n)))
        rows = int(np.ceil(n / cols))
        fig, axes = plt.subplots(rows, cols,
                                 figsize=(cols * 1.4, rows * 1.4))
        for i, ax in enumerate(np.atleast_1d(axes).ravel()):
            ax.axis("off")
            if i < n:
                ax.imshow(imgs[i], cmap="gray")
        fig.savefig(path, dpi=80)
        plt.close(fig)


class MultiHistogramPlotter(PlotterBase):
    """Grid of histograms, one per named tensor — e.g. every layer's
    weights at once (ref MultiHistogram, veles/plotting_units.py)."""

    def __init__(self, workflow, sources=None, bins=30, **kwargs):
        super(MultiHistogramPlotter, self).__init__(workflow, **kwargs)
        #: dict name → array-or-callable, or a callable returning a dict
        self.sources = sources
        self.bins = bins

    def payload(self):
        src = self.sources() if callable(self.sources) else self.sources
        if not src:
            return None
        hists = []
        for name in sorted(src):
            v = src[name]() if callable(src[name]) else src[name]
            counts, edges = np.histogram(np.asarray(v).ravel(),
                                         bins=self.bins)
            hists.append({"name": name, "counts": counts.tolist(),
                          "edges": edges.tolist()})
        return {"kind": "multi_histogram", "histograms": hists}

    def render(self, payload, path):
        plt = _matplotlib()
        hists = payload["histograms"]
        n = len(hists)
        cols = int(np.ceil(np.sqrt(n)))
        rows = int(np.ceil(n / cols))
        fig, axes = plt.subplots(rows, cols,
                                 figsize=(cols * 3.2, rows * 2.4))
        for i, ax in enumerate(np.atleast_1d(axes).ravel()):
            if i >= n:
                ax.axis("off")
                continue
            h = hists[i]
            edges = np.asarray(h["edges"])
            ax.bar(edges[:-1], h["counts"], width=np.diff(edges),
                   align="edge")
            ax.set_title(h["name"], fontsize=8)
            ax.tick_params(labelsize=6)
        fig.tight_layout()
        fig.savefig(path, dpi=80)
        plt.close(fig)


class MinMaxPlotter(PlotterBase):
    """Envelope of a tensor over epochs: min/mean/max curves with a
    filled band (ref the max-min accumulator plotters,
    veles/plotting_units.py:52-822)."""

    def __init__(self, workflow, source=None, ylabel="value", **kwargs):
        super(MinMaxPlotter, self).__init__(workflow, **kwargs)
        self.source = source
        self.ylabel = ylabel
        self.mins, self.means, self.maxs = [], [], []

    def payload(self):
        v = self.source() if callable(self.source) else self.source
        if v is None:
            return None
        arr = np.asarray(v).ravel()
        self.mins.append(float(arr.min()))
        self.means.append(float(arr.mean()))
        self.maxs.append(float(arr.max()))
        return {"kind": "minmax", "min": list(self.mins),
                "mean": list(self.means), "max": list(self.maxs),
                "ylabel": self.ylabel}

    def render(self, payload, path):
        plt = _matplotlib()
        fig, ax = plt.subplots(figsize=(6, 4))
        xs = np.arange(len(payload["mean"]))
        ax.fill_between(xs, payload["min"], payload["max"], alpha=0.25)
        ax.plot(xs, payload["mean"], marker="o", markersize=3)
        ax.plot(xs, payload["min"], linewidth=0.8)
        ax.plot(xs, payload["max"], linewidth=0.8)
        ax.set_xlabel("epoch")
        ax.set_ylabel(payload["ylabel"])
        ax.grid(True, alpha=0.3)
        fig.savefig(path, dpi=80)
        plt.close(fig)


class UnitStatsPlotter(PlotterBase):
    """Per-unit cumulative run time plus per-device live HBM bytes — the
    TPU-era equivalent of the reference's slave-stats plotter
    (veles/plotting_units.py:52-822: per-slave job/time tables became
    per-unit/per-device charts once the slaves became mesh shards)."""

    def __init__(self, workflow, top=10, **kwargs):
        super(UnitStatsPlotter, self).__init__(workflow, **kwargs)
        self.top = top

    def payload(self):
        wf = self.workflow
        if wf is None:
            return None
        units = sorted(
            ({"name": u.name, "runs": int(getattr(u, "run_count", 0)),
              "time": float(getattr(u, "run_time", 0.0))}
             for u in wf.units),
            key=lambda u: -u["time"])[:self.top]
        from veles_tpu.benchmark import Watcher
        try:
            memory = {str(k): int(v)
                      for k, v in Watcher.live_bytes().items()}
        except Exception:   # noqa: BLE001 — backend without live arrays
            memory = {}
        return {"kind": "unit_stats", "units": units, "memory": memory}

    def render(self, payload, path):
        plt = _matplotlib()
        units = payload["units"]
        memory = payload["memory"]
        fig, axes = plt.subplots(1, 2 if memory else 1, figsize=(9, 4))
        axes = np.atleast_1d(axes)
        names = [u["name"] for u in units]
        axes[0].barh(range(len(units)), [u["time"] for u in units])
        axes[0].set_yticks(range(len(units)), names, fontsize=7)
        axes[0].invert_yaxis()
        axes[0].set_xlabel("total run s")
        if memory:
            devs = sorted(memory)
            axes[-1].bar(range(len(devs)),
                         [memory[d] / 2**20 for d in devs])
            axes[-1].set_xticks(range(len(devs)),
                                [d[-8:] for d in devs], fontsize=7,
                                rotation=45)
            axes[-1].set_ylabel("live MiB")
        fig.tight_layout()
        fig.savefig(path, dpi=80)
        plt.close(fig)


class HistogramPlotter(PlotterBase):
    """Histogram of a tensor (ref plotting_units histogram family)."""

    def __init__(self, workflow, source=None, bins=50, **kwargs):
        super(HistogramPlotter, self).__init__(workflow, **kwargs)
        self.source = source
        self.bins = bins

    def payload(self):
        v = self.source() if callable(self.source) else self.source
        if v is None:
            return None
        counts, edges = np.histogram(np.asarray(v).ravel(), bins=self.bins)
        return {"kind": "histogram", "counts": counts.tolist(),
                "edges": edges.tolist()}

    def render(self, payload, path):
        plt = _matplotlib()
        fig, ax = plt.subplots(figsize=(6, 4))
        edges = np.asarray(payload["edges"])
        ax.bar(edges[:-1], payload["counts"],
               width=np.diff(edges), align="edge")
        fig.savefig(path, dpi=80)
        plt.close(fig)
