"""Pod master + per-host supervisor agents — the TPU-era multi-node
Launcher (ref: veles/launcher.py + server.py/client.py, the Twisted/
ZeroMQ master–slave control plane that respawned dead slaves and
requeued their work; PAPER.md §L4).

PR 8's :mod:`~veles_tpu.services.supervisor` survives anything on ONE
host.  On a pod the failure mode is qualitatively different: in
multi-controller SPMD a dead or stalled host does not crash the
survivors — they **hang in the next collective**.  So restart must be
detected pod-wide and executed pod-wide, from a checkpoint every host
actually committed:

* one **pod master** (this module's :class:`PodMaster`, ``veles-tpu-pod``)
  owns the pod policy over a small line-JSON TCP control plane (no new
  dependencies — the paper's Twisted protocol collapsed to sockets);
* one **per-host agent** (:class:`PodAgent`, ``veles-tpu-pod --agent``)
  per host spawns/kills the local worker (the training command, with the
  ``jax.distributed`` coordinator/process-id threaded in via the
  ``VELES_TPU_*`` env), classifies its deaths with the same
  :func:`~veles_tpu.services.supervisor.classify_exit` taxonomy the
  single-host supervisor uses, heartbeats liveness + step progress (the
  ``VELES_TPU_PROGRESS_FILE`` bridge in :mod:`telemetry.health`), and
  scans its host-local checkpoint directory for the agreement.

**Pod-level death classification** (any one triggers ONE coordinated
restart): a worker exit on ANY host; an agent silent past
``stale_after_ms``; or the **collective-hang latch** — every worker
alive and heartbeating but zero step/commit progress pod-wide for
``hang_seconds``.

**Coordinated restart**: every agent escalates SIGTERM →
(``kill_grace_ms``) → SIGKILL on its worker; the master collects each
host's manifest scan and computes the restart checkpoint by
**cross-host agreement** (:func:`snapshotter.agree_commits` — the
newest commit whose integrity manifest is valid on ALL hosts; a commit
present on host 0 but torn/absent on host 1 is rolled back pod-wide);
each agent rolls its directory back (:func:`snapshotter.
rollback_to_commit`) and respawns its worker under a new **fenced
incarnation id** on a fresh coordinator port — a zombie worker from a
previous incarnation can neither re-register (refused:
stale-incarnation) nor rejoin the collective (different coordinator).

PR 8's valves are lifted to pod scope (:class:`PodValves`): bounded
restarts per window, and identical pod-wide crash signatures with zero
agreed-checkpoint progress give up early.

**Elastic tier** (the Veles reference's slaves-leave-and-join
elasticity, server.py:637-655, mapped onto SPMD): a host whose agent
misses ``pod.loss_strikes`` consecutive agreement windows is classified
**permanently lost** — the pod *degrades* to the survivors instead of
retrying the dead topology: one resize-bucketed coordinated restart
respawns the workers under a mesh rebuilt from the live host set
(process ids remapped contiguous, ``parallel.mesh.fit_axes_to_devices``
rescales a fixed data axis) resuming from the survivors' agreed
checkpoint, which the snapshotter reshards onto the smaller topology
(``snapshotter.reshard_state`` — per-leaf bit-exact; global loader
order and PRNG words proven invariant).  When the lost host's agent
re-registers, one **re-expand** restart folds it back in: the agreed
commit is replicated to its frozen ring over the control plane
(``fetch_commit``/``push_commit``) unless it already holds it, and the
pod returns to full size.  Planned resizes live in their own valve
bucket — they can never consume the crash-loop or deterministic-bug
budget.  Gate: ``tools/pod_chaos.py`` (``--host-loss`` flavor); docs:
docs/distributed_training.md "Pod orchestration"."""

import argparse
import json
import logging
import os
import queue
import random
import signal
import socket
import subprocess
import sys
import threading
import time

from veles_tpu.config import root
from veles_tpu.services.supervisor import (STARTUP_FLAKE_OUTPUT_LIMIT,
                                           STARTUP_FLAKE_SIGNALS,
                                           backoff_delay, classify_exit,
                                           newest_mtime)
from veles_tpu.telemetry import flight


def _free_port(host="127.0.0.1"):
    s = socket.socket()
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


def merge_config_list(argv, statements):
    """Insert config statements into an argv's existing ``--config-list``
    (argparse ``nargs="*"`` keeps only the LAST flag instance, so a
    second flag would silently drop the command's own overrides), or
    append a fresh flag when there is none."""
    argv = list(argv)
    statements = list(statements)
    if not statements:
        return argv
    if "--config-list" in argv:
        i = argv.index("--config-list") + 1
        while i < len(argv) and not argv[i].startswith("--"):
            i += 1
        return argv[:i] + statements + argv[i:]
    return argv + ["--config-list"] + statements


def merge_worker_env(inherited, spec_env):
    """The worker's env: ``inherited`` (the agent's environment)
    updated with the spawn spec's delta — except ``XLA_FLAGS``, where
    the pod's device-count flag is APPENDED to the operator's own
    flags instead of clobbering them (the pod's flag last, so it wins
    a conflict)."""
    env = dict(inherited)
    spec_env = dict(spec_env)
    if "XLA_FLAGS" in spec_env and env.get("XLA_FLAGS"):
        spec_env["XLA_FLAGS"] = "%s %s" % (env["XLA_FLAGS"],
                                           spec_env["XLA_FLAGS"])
    env.update(spec_env)
    return env


def _proc_start_ticks(pid):
    """Kernel start time (clock ticks since boot) of ``pid`` from
    ``/proc/<pid>/stat``, or None where /proc is unavailable.  The
    (pid, start-ticks) pair identifies one process LIFE: a recycled
    pid gets a different start time."""
    try:
        with open("/proc/%d/stat" % pid, "rb") as f:
            data = f.read()
        # comm (field 2) may contain spaces/parens — field 22 counts
        # from after the LAST closing paren
        return int(data.rsplit(b")", 1)[1].split()[19])
    except (OSError, ValueError, IndexError):
        return None


# =====================================================================
# the pure pod-policy core (no sockets, no processes — unit-tested
# directly in tests/test_podmaster.py)
# =====================================================================

class IncarnationFence(object):
    """Monotonic incarnation ids with registration fencing: a worker
    (or a rejoining agent that still carries one) registering under any
    incarnation other than the current one is refused — the zombie from
    a previous life must not rejoin the pod."""

    def __init__(self):
        self.incarnation = 0
        self.refusals = []

    def bump(self):
        self.incarnation += 1
        return self.incarnation

    def admit(self, host, incarnation, now=None):
        """None = admitted; otherwise the refusal reason string
        (recorded)."""
        if incarnation is None or incarnation == self.incarnation:
            return None
        reason = ("stale-incarnation"
                  if incarnation < self.incarnation
                  else "future-incarnation")
        self.refusals.append(
            {"host": host, "incarnation": incarnation,
             "current": self.incarnation, "reason": reason,
             "ts": now if now is not None else time.time()})
        return reason


def classify_stall(now, hosts, hang_seconds, stale_after):
    """Pod-level stall classification from heartbeat/progress inputs.

    :param hosts: ``{host: {"heartbeat_ts", "progress_ts",
        "worker_alive"}}`` — ``progress_ts`` starts at the worker's
    spawn time (startup grace) and advances with the step/commit
    progress the agent observes.
    :returns: None, or ``{"cause": "stale-heartbeat"|"collective-hang",
        "hosts": [...]}``.

    A silent agent is its own cause.  The hang latch requires EVERY
    worker alive (a dead worker is the worker-exit trigger's job) and
    zero progress pod-wide: one stalled host is enough to freeze the
    whole pod — the survivors block inside their next collective, so
    per-host progress goes flat *everywhere at once*, which is exactly
    the latch condition."""
    if not hosts:
        return None
    stale = [h for h, s in sorted(hosts.items())
             if s.get("heartbeat_ts") is None
             or now - s["heartbeat_ts"] > stale_after]
    if stale:
        return {"cause": "stale-heartbeat", "hosts": stale}
    if not all(s.get("worker_alive") for s in hosts.values()):
        return None
    newest = max(s.get("progress_ts") or 0.0 for s in hosts.values())
    if now - newest > hang_seconds:
        return {"cause": "collective-hang", "hosts": sorted(hosts)}
    return None


class PodValves(object):
    """PR 8's crash-loop and deterministic-bug valves lifted to pod
    scope: one decision per coordinated restart.  Planned topology
    changes — the degraded restart after a permanent host loss and the
    re-expand restart when capacity returns — are accounted in their
    OWN bucket (``resize_restarts``): a resize is the pod doing its
    job, and it must never consume the crash-loop window or feed the
    deterministic-bug signature counter."""

    def __init__(self, max_restarts, window_seconds,
                 deterministic_limit, scale_max_per_window=4,
                 scale_window_seconds=120.0):
        self.max_restarts = int(max_restarts)
        self.window_seconds = float(window_seconds)
        self.deterministic_limit = int(deterministic_limit)
        self._window = []
        self._last_signature = None
        self._same_signature = 0
        #: degraded/re-expand restarts — their own bucket, never the
        #: crash-loop window
        self.resize_restarts = 0
        #: serving-fleet autoscale decisions — a THIRD bucket (see
        #: :meth:`admit_scale`): bounded per window for flap damping,
        #: and like resizes never the crash-loop window
        self.scale_max_per_window = int(scale_max_per_window)
        self.scale_window_seconds = float(scale_window_seconds)
        self.scale_events = 0
        self.scale_damped = 0
        self._scale_window = []

    def admit(self, now, signature=None, progressed=False,
              counted=True, resize=False, sticky_signature=False):
        """Decide one pod restart: ``"respawn"``, ``"crash-loop"`` or
        ``"deterministic-bug"``.

        :param signature: the pod-wide crash signature — a tuple of the
            per-host crash signatures, or None when the round had none
            (kills, hangs).
        :param progressed: the agreed checkpoint advanced since the
            previous restart — a pod that keeps committing is working,
            however it keeps dying (resets the deterministic counter).
        :param counted: False for restarts that must stay unbounded —
            pod-wide graceful preemption and environment startup
            flakes.
        :param resize: a PLANNED topology change (degrade after
            permanent host loss, re-expand on capacity return): counts
            only in ``resize_restarts`` — neither the crash-loop window
            nor the deterministic counter moves.
        :param sticky_signature: judge the signature REGARDLESS of
            checkpoint progress — the numeric-fault class
            (``numerics:<kind>`` exits, services.sentinel): a
            diverging run commits plenty while it replays, but
            identical divergence across restarts is deterministic all
            the same."""
        if progressed and not sticky_signature:
            self._same_signature, self._last_signature = 0, None
        if resize:
            self.resize_restarts += 1
            return "respawn"
        if not counted:
            return "respawn"
        if signature:
            if signature == self._last_signature:
                self._same_signature += 1
            else:
                self._last_signature = signature
                self._same_signature = 1
            if (not progressed or sticky_signature) and \
                    self._same_signature >= self.deterministic_limit:
                return "deterministic-bug"
        self._window = [t for t in self._window
                        if now - t < self.window_seconds]
        self._window.append(now)
        if len(self._window) > self.max_restarts:
            return "crash-loop"
        return "respawn"

    def admit_scale(self, now):
        """Decide one serving-fleet AUTOSCALE step: ``"scale"`` or
        ``"damped"``.  Scale decisions live in their own budget
        (``scale_max_per_window`` per ``scale_window_seconds``) — flap
        damping: an oscillating load signal is throttled here, and a
        scale storm can never consume the crash-loop window or feed
        the deterministic-bug counter (those guard replica CRASHES,
        which are a different failure)."""
        self._scale_window = [t for t in self._scale_window
                              if now - t < self.scale_window_seconds]
        if len(self._scale_window) >= self.scale_max_per_window:
            self.scale_damped += 1
            return "damped"
        self._scale_window.append(now)
        self.scale_events += 1
        return "scale"


# =====================================================================
# the serving-fleet policy core (pure — unit-tested in
# tests/test_fleet.py without sockets or subprocesses)
# =====================================================================

class FleetAutoscaler(object):
    """The closed-loop capacity controller for the serving fleet.

    The scale-UP signal is the one the platform already measures: the
    SLO shedder's queue-wait overshoot (``SloShedder.overshoot``, read
    off every replica's ``/health`` by the router's probes) and fresh
    ``serve.shed`` rejections — both mean the fleet is turning real
    traffic away, so capacity should follow the load instead
    (PAPERS.md's TVM/CLBlast thesis: measured feedback drives
    configuration).  Scale-DOWN needs ``idle_s`` of sustained
    fleet-wide idle (no queued/in-flight work, zero overshoot) — and
    the caller always routes it through the SIGTERM drain, so
    shrinking never loses a request.  ``cooldown_s`` spaces
    consecutive decisions; the caller additionally budgets every
    decision through :meth:`PodValves.admit_scale` (flap damping).

    Pure: :meth:`decide` takes the clock and the signals as arguments
    and returns ``(delta, reason)`` with ``delta`` in ``(+1, -1, 0)``
    — one replica per decision, because each decision's effect has to
    be measured before the next (the controller is closed-loop, not
    predictive)."""

    def __init__(self, up_overshoot=1.0, idle_s=30.0, cooldown_s=10.0,
                 up_prefill_backlog=0):
        self.up_overshoot = float(up_overshoot)
        self.idle_s = float(idle_s)
        self.cooldown_s = float(cooldown_s)
        #: fleet-wide queued-but-unprefilled prompt tokens that count
        #: as overload on their own (0 = off): a prefill backlog
        #: PREDICTS the queue-wait breach, so capacity can arrive
        #: before the shedder ever has to measure one
        self.up_prefill_backlog = int(up_prefill_backlog or 0)
        self._idle_since = None
        self._last_scale_ts = None
        self._last_shed_total = None

    def decide(self, now, desired, minimum, maximum, signals):
        """One control step.  ``signals``: ``{"overshoot": float,
        "shed_total": int (monotonic), "prefill_backlog": int,
        "busy": bool}`` — the shape
        :meth:`FleetRouter.fleet_signals` returns."""
        overshoot = float(signals.get("overshoot") or 0.0)
        shed_total = int(signals.get("shed_total") or 0)
        backlog = int(signals.get("prefill_backlog") or 0)
        busy = bool(signals.get("busy"))
        if self._last_shed_total is None:
            self._last_shed_total = shed_total
        shed_delta = max(shed_total - self._last_shed_total, 0)
        self._last_shed_total = shed_total
        overloaded = (overshoot >= self.up_overshoot > 0) \
            or shed_delta > 0 \
            or (backlog >= self.up_prefill_backlog > 0)
        # idle tracking runs on EVERY step (including cooldown ones):
        # the idle clock must not reset just because a decision was
        # recently made
        if overloaded or busy or overshoot > 0 or backlog > 0:
            self._idle_since = None
        elif self._idle_since is None:
            self._idle_since = now
        if self._last_scale_ts is not None \
                and now - self._last_scale_ts < self.cooldown_s:
            return 0, "cooldown"
        if overloaded:
            if desired >= maximum:
                return 0, "overloaded at max=%d" % maximum
            self._last_scale_ts = now
            return (+1, "overshoot=%.2f shed_delta=%d backlog=%d"
                    % (overshoot, shed_delta, backlog))
        if self._idle_since is not None \
                and now - self._idle_since >= self.idle_s:
            if desired <= minimum:
                return 0, "idle at min=%d" % minimum
            self._last_scale_ts = now
            return -1, "idle %.0fs" % (now - self._idle_since)
        return 0, None


def plan_fleet(desired, live_hosts, per_host, placements,
               draining=(), drainable=None):
    """Reconcile the declarative fleet spec against what is live:
    returns ``(spawn_hosts, drain_reps)``.

    :param desired: target replica count (already min/max-clamped).
    :param live_hosts: hosts with a LIVE agent (sorted ids) — the
        only legal spawn targets; a lost host's replicas simply stop
        appearing in ``placements`` and this planner re-places them
        on the survivors (replacement-on-host-death is reconciliation,
        not a special case).
    :param per_host: max replicas on any one host (the fleet spec).
    :param placements: ``{rep_id: host}`` of replicas that are
        spawning or ready.
    :param draining: rep_ids already draining (they still occupy
        their host slot until gone, but count toward neither desired
        nor further drains).
    :param drainable: rep_ids eligible for a scale-down drain
        (default: all of ``placements``).  The master passes the
        READY set — a replica still spawning is not serving anything,
        so "draining" it is meaningless; it is left to finish and
        gets drained on a later round if still surplus.

    Deterministic: spawns fill the least-loaded live host first (ties
    to the lowest id); drains shed the NEWEST replica on the
    most-loaded host first (the oldest replicas keep their warmed
    prefix caches)."""
    draining = set(draining)
    active = {r: h for r, h in placements.items() if r not in draining}
    load = {h: 0 for h in live_hosts}
    for rep, host in active.items():
        if host in load:
            load[host] += 1
    for rep, host in placements.items():
        if rep in draining and host in load:
            load[host] += 1     # a draining replica still holds a slot
    live_count = sum(1 for h in active.values() if h in load)
    spawns = []
    for _ in range(max(desired - live_count, 0)):
        free = [h for h in live_hosts if load[h] < per_host]
        if not free:
            break               # spec unsatisfiable on the live hosts
        host = min(free, key=lambda h: (load[h], h))
        load[host] += 1
        spawns.append(host)
    drains = []
    eligible = set(placements) if drainable is None else set(drainable)
    for _ in range(max(live_count - desired, 0)):
        candidates = [(r, h) for r, h in active.items()
                      if h in load and r not in drains
                      and r in eligible]
        if not candidates:
            break
        rep, host = max(candidates,
                        key=lambda rh: (load[rh[1]], rh[0]))
        load[host] -= 1
        del active[rep]
        drains.append(rep)
    return spawns, drains


def dead_replica_verdicts(reps, router_states, agent_alive):
    """Classify which replicas are DEAD and why — pure, fed by the
    master's tick.  ``reps``: ``{rep_id: {"host", "state", "rid"}}``
    (manager view), ``router_states``: ``{router rid: "up"|"down"|
    "draining"}`` (the router's health verdicts), ``agent_alive``:
    ``{host: bool}``.

    Returns ``[(rep_id, cause)]``.  Two causes:

    * ``"host-death"`` — the router marked the replica down AND its
      host's agent connection is gone: the machine died.  Detection
      rides the router's health probe (≤ one interval) instead of the
      slower host-loss strike ladder — the strikes decide where new
      work may be PLACED, not how fast a dead replica is replaced.
    * ``"down"`` — the router marked it down while the agent is still
      there: the replica process itself is sick/unreachable; the
      agent's ``replica_exit`` (with the supervisor taxonomy) usually
      lands first, this is the belt-and-braces path for a wedged-but-
      alive process."""
    out = []
    for rep_id, rec in sorted(reps.items()):
        if rec.get("state") != "ready":
            continue
        if router_states.get(rec.get("rid")) != "down":
            continue
        cause = ("host-death"
                 if not agent_alive.get(rec.get("host"), False)
                 else "down")
        out.append((rep_id, cause))
    return out


# =====================================================================
# line-JSON transport
# =====================================================================

class _Conn(object):
    """One line-JSON peer: locked sends, file-buffered reads."""

    def __init__(self, sock):
        self.sock = sock
        self.rfile = sock.makefile("r", encoding="utf-8")
        self._wlock = threading.Lock()
        self.alive = True

    def send(self, obj):
        data = (json.dumps(obj) + "\n").encode("utf-8")
        try:
            with self._wlock:
                self.sock.sendall(data)
            return True
        except OSError:
            self.alive = False
            return False

    def recv(self):
        """One decoded message, or None on EOF/error."""
        try:
            line = self.rfile.readline()
        except OSError:
            return None
        if not line:
            return None
        try:
            msg = json.loads(line)
        except ValueError:
            return {"type": "garbage", "line": line[:200]}
        return msg if isinstance(msg, dict) else \
            {"type": "garbage", "line": line[:200]}

    def close(self):
        self.alive = False
        for closer in (self.rfile.close, self.sock.close):
            try:
                closer()
            except OSError:
                pass


# =====================================================================
# the pod master
# =====================================================================

class PodMaster(object):
    """Coordinate ``n_hosts`` per-host agents around one training
    command (see the module docstring for the policy).

    :param argv: the worker command line (e.g. ``[sys.executable, "-m",
        "veles_tpu", "wf.py", "--snapshot", "auto", ...]``); the master
        threads per-host snapshot dirs + ``snapshot.per_host`` into its
        ``--config-list`` and per-host/incarnation env on top.
    :param snapshot_root: per-host snapshot dirs live at
        ``<snapshot_root>/host<i>``.
    :param prefix: the workflow's snapshot prefix (checkpoint names =
        ``<prefix>_<suffix>``) — what the agreement scans for.
    :param host_extras: ``{host: [config statements]}`` merged into that
        host's worker ``--config-list`` (chaos harnesses inject per-host
        stalls this way).
    :param spawn_agents: launch the N agents as local subprocesses (the
        single-machine pod used by tests/CI).  False prints the agent
        command for each host instead — real pods run one agent per
        machine.
    """

    def __init__(self, argv, n_hosts=2, snapshot_root=None, prefix=None,
                 host_extras=None, workdir=None, port=0,
                 bind_host="127.0.0.1", coordinator_host="127.0.0.1",
                 devices_per_host=None, env=None, spawn_agents=True,
                 heartbeat_ms=None, stale_after_ms=None,
                 hang_seconds=None, kill_grace_ms=None,
                 max_restarts=None, window_seconds=None,
                 deterministic_limit=None, backoff_base_ms=None,
                 backoff_max_ms=None, seed=None, elastic=None,
                 loss_strikes=None, loss_window_s=None,
                 reexpand=None, replicate_max_mb=None):
        def knob(value, key, default):
            if value is not None:
                return value
            return root.common.pod.get(key, default)

        self.argv = list(argv)
        self.n_hosts = int(n_hosts)
        self.workdir = os.path.abspath(workdir or "pod-workdir")
        self.snapshot_root = os.path.abspath(
            snapshot_root or os.path.join(self.workdir, "snapshots"))
        self.prefix = prefix or "wf"
        self.host_extras = dict(host_extras or {})
        self.port = int(port)
        self.bind_host = bind_host
        self.coordinator_host = coordinator_host
        self.devices_per_host = devices_per_host
        self.env = env
        self.spawn_agents = bool(spawn_agents)
        self.heartbeat_s = float(
            knob(heartbeat_ms, "heartbeat_ms", 500)) / 1e3
        self.stale_after_s = float(
            knob(stale_after_ms, "stale_after_ms", 10000)) / 1e3
        self.hang_seconds = float(knob(hang_seconds, "hang_seconds", 300))
        self.kill_grace_s = float(
            knob(kill_grace_ms, "kill_grace_ms", 5000)) / 1e3
        self.backoff_base = float(
            knob(backoff_base_ms, "backoff_base_ms", 200)) / 1e3
        self.backoff_max = float(
            knob(backoff_max_ms, "backoff_max_ms", 10000)) / 1e3
        self.valves = PodValves(
            knob(max_restarts, "max_restarts", 8),
            knob(window_seconds, "window_seconds", 600),
            knob(deterministic_limit, "deterministic_limit", 3))
        #: elastic pod: continue DEGRADED on the survivors after a
        #: permanent host loss instead of retrying the dead topology
        #: until the crash-loop valve gives up
        self.elastic = bool(knob(elastic, "elastic", True))
        #: consecutive coordinated restarts in which the same host's
        #: agent never re-registered within its window before the loss
        #: is classified PERMANENT (and, with ``elastic``, the pod
        #: degrades to the survivors)
        self.loss_strikes = int(knob(loss_strikes, "loss_strikes", 2))
        #: how long each round's agreement waits for a silent host's
        #: agent before striking it
        self.loss_window_s = float(
            knob(loss_window_s, "loss_window_s", 60))
        #: trigger a re-expand restart back to full size when a lost
        #: host's agent re-registers
        self.reexpand = bool(knob(reexpand, "reexpand", True))
        #: re-expand checkpoint replication cap: the agreed commit is
        #: shipped to the returning host over the control plane (its
        #: ring is stale); past this size, replication is refused and
        #: the pod stays degraded (real pods with shared storage never
        #: need the transfer — the returning host already sees the
        #: commit)
        self.replicate_max_mb = float(
            knob(replicate_max_mb, "replicate_max_mb", 64))
        self.fence = IncarnationFence()
        self._rng = random.Random(seed)
        self._log = logging.getLogger("PodMaster")
        self._lock = threading.Lock()
        # lint-ok: VT804 — control-plane inbox: producers are the
        # per-agent reader threads (bounded by pod size), the policy
        # loop drains every cycle, and register/exit events must never
        # be dropped or block the readers (BoundedStream semantics
        # would do both)
        self._inbox = queue.Queue()
        self._listener = None
        self._threads = []
        self._agent_procs = {}
        self._agent_spawns = {}
        self._stopping = False
        self.phase = "gathering"
        self.rc = None
        #: per-host live state (the policy thread's view)
        self.hosts = {h: self._fresh_host() for h in range(self.n_hosts)}
        #: one record per coordinated restart
        self.history = []
        self.restart_causes = []
        self._last_agreed = None
        self._last_agreed_key = None
        self._round_exits = {}
        self._round_cause = None
        self._round_started = None
        self._consecutive = 0
        #: consecutive env-flake rounds with zero checkpoint progress —
        #: flakes respawn uncounted (they must not burn the crash-loop
        #: budget), but an endless storm of them with the pod going
        #: nowhere is its own giveup condition
        self._flake_streak = 0
        self.flake_streak_limit = 6
        #: hosts classified as PERMANENTLY lost — the pod runs degraded
        #: on the complement until their agents re-register
        self.lost_hosts = set()
        #: consecutive agreement windows each host's agent missed
        self.absence_strikes = {h: 0 for h in range(self.n_hosts)}
        #: specs queued for hosts whose agent was unregistered at spawn
        #: time (delivered if/when the agent registers in the same
        #: incarnation)
        self._pending_specs = {}
        #: the hosts the current incarnation was spawned on
        self._spawn_targets = set(range(self.n_hosts))
        #: the re-expand replication context (source/need/files/...)
        self._replication = None
        #: a failed re-expand (replication error) blocks re-triggering
        #: until the lost host's agent re-registers
        self._reexpand_blocked = set()
        #: host -> wall ts of the failed transfer: a blocked host whose
        #: agent stays connected (so no fresh ``agent_up`` ever clears
        #: the block) re-probes after a cooldown instead of running
        #: degraded forever
        self._reexpand_block_ts = {}
        self._gauges = None

    @staticmethod
    def _fresh_host():
        return {"conn": None, "registered_ts": None,
                "heartbeat_ts": None, "progress_ts": None,
                "worker_alive": False, "worker_pid": None,
                "spawned_ts": None, "last_exit": None, "up_inc": None}

    # ------------------------------------------------------------ layout
    def host_snapshot_dir(self, host):
        return os.path.join(self.snapshot_root, "host%d" % host)

    def host_workdir(self, host):
        return os.path.join(self.workdir, "agent%d" % host)

    def host_down_file(self, host):
        """Marker file that keeps the local agent emulation from
        respawning this host's agent — how tests and the chaos harness
        model a machine that is GONE (real pods simply have no agent
        process to register).  Remove it to model capacity returning."""
        return os.path.join(self.workdir, "host%d.down" % host)

    def live_hosts(self):
        return sorted(h for h in self.hosts if h not in self.lost_hosts)

    # --------------------------------------------------------- telemetry
    def _export_pod_size(self):
        """``veles_pod_hosts`` / ``veles_pod_degraded`` gauges — the
        operator's one-glance answer to "how big is the pod right now"
        (fail-soft: telemetry must never take the pod down)."""
        try:
            from veles_tpu import telemetry
            if self._gauges is None:
                self._gauges = (
                    telemetry.registry.gauge(
                        "veles_pod_hosts",
                        "hosts the pod is currently running on"),
                    telemetry.registry.gauge(
                        "veles_pod_degraded",
                        "1 while the pod runs degraded after a "
                        "permanent host loss"))
            self._gauges[0].set(len(self.live_hosts()))
            self._gauges[1].set(1 if self.lost_hosts else 0)
        except Exception:   # noqa: BLE001 — fail-soft
            pass

    def agent_argv(self, host):
        return [sys.executable, "-m", "veles_tpu.services.podmaster",
                "--agent", "--master",
                "%s:%d" % (self.bind_host, self.port),
                "--host-id", str(host),
                "--workdir", self.host_workdir(host)]

    def worker_spec(self, host, incarnation, coordinator_port,
                    agreed=None, rollback=False, quarantine=None,
                    live=None):
        """The spawn message for one host/incarnation — argv with the
        per-host snapshot config merged in, plus the env delta that
        threads the ``jax.distributed`` identity and the fenced
        incarnation into the worker.

        :param live: the hosts this incarnation spawns on (default: all
            of them).  A degraded incarnation passes the survivor set:
            process ids are remapped contiguous over it, the worker
            count shrinks to it, and the workers' mesh is rebuilt from
            the LIVE device set (``pod.elastic_mesh`` →
            :func:`parallel.mesh.fit_axes_to_devices`) instead of the
            configured topology."""
        live = sorted(live) if live is not None else \
            sorted(self.hosts)
        process_id = live.index(host)
        degraded = len(live) < self.n_hosts
        statements = [
            "root.common.dirs.snapshots=%r" % self.host_snapshot_dir(host),
            "root.common.snapshot.per_host=True",
            # the cross-host agreement verifies integrity manifests; a
            # config with snapshot.manifest=False would leave every
            # commit unverifiable and a single restart would quarantine
            # the whole ring — force them on under the pod
            "root.common.snapshot.manifest=True",
            # agreement scans FILE commits (one pickle + manifest
            # sidecar per commit); the orbax/db backends have no
            # per-commit file sha to intersect, so a pod running them
            # would find every commit unverifiable on the first
            # restart — force the file backend under the pod
            "root.common.snapshot.backend='file'",
            "root.common.blackbox.dir=%r" % os.path.join(
                self.workdir, "dumps"),
            # the worker builds its mesh from the LIVE device set: a
            # fixed --mesh data axis rescales to the survivors instead
            # of failing on a topology that no longer exists
            "root.common.pod.elastic_mesh=True",
            # surfaced through the worker's web_status /api/health so
            # an operator probing any host sees the pod's true size
            "root.common.pod.size=%d" % len(live),
            "root.common.pod.total=%d" % self.n_hosts,
            "root.common.pod.degraded=%r" % degraded,
            "root.common.pod.lost_hosts=%r" % sorted(self.lost_hosts),
        ] + list(self.host_extras.get(host, ()))
        env = {
            "VELES_TPU_COORDINATOR": "%s:%d" % (self.coordinator_host,
                                                coordinator_port),
            "VELES_TPU_NUM_PROCESSES": str(len(live)),
            "VELES_TPU_PROCESS_ID": str(process_id),
            "VELES_TPU_INCARNATION": str(incarnation),
        }
        if self.devices_per_host:
            env["XLA_FLAGS"] = \
                "--xla_force_host_platform_device_count=%d" \
                % self.devices_per_host
        return {"type": "spawn", "incarnation": incarnation,
                "argv": merge_config_list(self.argv, statements),
                "env": env, "prefix": self.prefix,
                "snapshot_dir": self.host_snapshot_dir(host),
                "blackbox_dir": os.path.join(self.workdir, "dumps"),
                "agreed": agreed, "rollback": bool(rollback),
                "quarantine": quarantine}

    # --------------------------------------------------------- lifecycle
    def start(self):
        os.makedirs(self.workdir, exist_ok=True)
        os.makedirs(os.path.join(self.workdir, "dumps"), exist_ok=True)
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((self.bind_host, self.port))
        self.port = self._listener.getsockname()[1]
        self._listener.listen(self.n_hosts + 4)
        t = threading.Thread(target=self._accept_loop,
                             name="PodAccept", daemon=True)
        t.start()
        self._threads.append(t)
        if self.spawn_agents:
            for h in range(self.n_hosts):
                self._spawn_agent(h)
        else:
            for h in range(self.n_hosts):
                print("[pod] host %d agent command: %s"
                      % (h, " ".join(self.agent_argv(h))), flush=True)
        self._policy_thread = threading.Thread(
            target=self._policy_loop, name="PodPolicy", daemon=True)
        self._policy_thread.start()
        self._info("pod master listening on %s:%d (%d hosts)",
                   self.bind_host, self.port, self.n_hosts)
        self._export_pod_size()
        return self

    def wait(self, timeout=None):
        """Block until the pod finishes/gives up; the final exit code
        (None on timeout)."""
        self._policy_thread.join(timeout)
        if self._policy_thread.is_alive():
            return None
        return self.rc

    def run(self):
        self.start()
        return self.wait()

    def stop(self, rc=1):
        """External stop: shut every agent (and its worker) down."""
        with self._lock:
            if self.phase in ("done", "giveup"):
                return
            self._stopping = True
        self._inbox.put(("stop", None, {"rc": rc}))

    def status(self):
        """One JSON-able snapshot — the chaos harness's observation
        surface."""
        with self._lock:
            return {
                "phase": self.phase,
                "incarnation": self.fence.incarnation,
                "rc": self.rc,
                "restarts": len(self.history),
                "restart_causes": list(self.restart_causes),
                "agreed": self._last_agreed,
                "fence_refusals": list(self.fence.refusals),
                "degraded": bool(self.lost_hosts),
                "lost_hosts": sorted(self.lost_hosts),
                "live_hosts": len(self.hosts) - len(self.lost_hosts),
                "absence_strikes": dict(self.absence_strikes),
                "resize_restarts": self.valves.resize_restarts,
                "hosts": {
                    h: {"worker_alive": s["worker_alive"],
                        "worker_pid": s["worker_pid"],
                        "registered": s["conn"] is not None,
                        "lost": h in self.lost_hosts,
                        "last_exit": s["last_exit"]}
                    for h, s in self.hosts.items()},
            }

    # --------------------------------------------------- agent processes
    def _spawn_agent(self, host):
        os.makedirs(self.host_workdir(host), exist_ok=True)
        env = dict(self.env if self.env is not None else os.environ)
        # the agents (and through them the workers) must import
        # veles_tpu from wherever THIS master imported it — the local
        # pod emulation runs uninstalled from the repo checkout
        import veles_tpu
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(veles_tpu.__file__)))
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else "")
        log = open(os.path.join(self.host_workdir(host), "agent.log"),
                   "ab")
        try:
            proc = subprocess.Popen(self.agent_argv(host), env=env,
                                    stdout=log, stderr=log)
        finally:
            log.close()
        self._agent_procs[host] = proc
        self._agent_spawns.setdefault(host, []).append(time.time())
        flight.record("pod.agent_spawn", host=host, pid=proc.pid)

    # ------------------------------------------------------ accept/reader
    def _accept_loop(self):
        while True:
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            conn = _Conn(sock)
            threading.Thread(target=self._reader, args=(conn,),
                             name="PodReader", daemon=True).start()

    def _reader(self, conn):
        msg = conn.recv()
        if not msg or msg.get("type") != "register":
            conn.send({"type": "refused", "reason": "register-first"})
            conn.close()
            return
        host = msg.get("host")
        reason = None
        with self._lock:
            if not isinstance(host, int) or host not in self.hosts:
                reason = "unknown-host"
            else:
                # FENCE FIRST: a registration carrying a previous
                # incarnation is a zombie trying to rejoin — refuse it
                # even when the slot looks free
                reason = self.fence.admit(host, msg.get("incarnation"))
            if reason is None and self.hosts[host]["conn"] is not None \
                    and self.hosts[host]["conn"].alive:
                reason = "duplicate-host"
            if reason is None:
                self.hosts[host]["conn"] = conn
                self.hosts[host]["registered_ts"] = time.time()
                self.hosts[host]["heartbeat_ts"] = time.time()
        if reason is not None:
            flight.record("pod.fence", host=host, reason=reason,
                          incarnation=msg.get("incarnation"),
                          current=self.fence.incarnation)
            conn.send({"type": "refused", "reason": reason,
                       "current": self.fence.incarnation})
            conn.close()
            return
        conn.send({"type": "welcome",
                   "incarnation": self.fence.incarnation,
                   "heartbeat_ms": int(self.heartbeat_s * 1e3)})
        flight.record("pod.agent_up", host=host)
        self._inbox.put(("agent_up", host, msg))
        while True:
            msg = conn.recv()
            if msg is None:
                break
            self._inbox.put((msg.get("type", "garbage"), host, msg))
        conn.close()
        self._inbox.put(("agent_lost", host, {}))

    def _send(self, host, obj):
        conn = self.hosts[host]["conn"]
        return conn is not None and conn.send(obj)

    # -------------------------------------------------------- policy loop
    def _policy_loop(self):
        try:
            self._policy_loop_inner()
        except Exception as e:   # noqa: BLE001 — never die silently
            self._error("pod policy loop crashed: %s: %s",
                        type(e).__name__, e)
            flight.record("pod.policy_error", error=str(e))
            flight.dump(reason="pod-policy-error", error=e)
            with self._lock:
                self.phase = "giveup"
                self.rc = 1
        finally:
            self._shutdown_agents()
            try:
                self._listener.close()
            except OSError:
                pass

    def _policy_loop_inner(self):
        while True:
            try:
                ev = self._inbox.get(timeout=0.2)
            except queue.Empty:
                ev = None
            if ev is not None:
                self._handle_event(*ev)
            self._tick()
            with self._lock:
                if self.phase in ("done", "giveup"):
                    return

    def _handle_event(self, kind, host, msg):
        now = time.time()
        if kind == "stop":
            with self._lock:
                self.phase = "giveup"
                self.rc = msg.get("rc", 1)
            flight.record("pod.stopped")
            return
        if host is None:
            return
        with self._lock:
            state = self.hosts[host]
            if kind == "agent_up":
                # a fresh registration retries a previously failed
                # re-expansion, and — during a respawn round — receives
                # the spec that was queued while its host was absent
                self._reexpand_blocked.discard(host)
                self._reexpand_block_ts.pop(host, None)
                spec = self._pending_specs.pop(host, None)
                if spec is not None and self.phase == "respawning" \
                        and spec.get("incarnation") \
                        == self.fence.incarnation:
                    self._send(host, spec)
            elif kind == "commit_data":
                rep = self._replication
                if rep is not None and host == rep.get("source"):
                    if msg.get("ok") and msg.get("files"):
                        rep["files"] = msg["files"]
                    else:
                        rep["error"] = msg.get("error",
                                               "fetch_commit failed")
            elif kind == "commit_pushed":
                rep = self._replication
                if rep is not None:
                    if msg.get("ok"):
                        rep["pushed"].add(host)
                    else:
                        rep["failed"].append(host)
                        rep["error"] = msg.get("error", "push failed")
            elif kind == "agent_lost":
                state["conn"] = None
                state["heartbeat_ts"] = None
                flight.record("pod.agent_lost", host=host)
            elif kind == "heartbeat":
                # heartbeats are NOT a fence point: between the
                # master's incarnation bump and the agent receiving its
                # spawn order, in-flight heartbeats legitimately carry
                # the previous incarnation — fencing here would kill
                # freshly spawned workers.  The fence points are
                # registration and worker_up.
                state["heartbeat_ts"] = now
                state["worker_alive"] = bool(msg.get("worker_alive"))
                age = msg.get("progress_age")
                if age is not None:
                    ts = now - float(age)
                    if state["progress_ts"] is None \
                            or ts > state["progress_ts"]:
                        state["progress_ts"] = ts
            elif kind == "worker_up":
                reason = self.fence.admit(host, msg.get("incarnation"))
                if reason is not None:
                    flight.record("pod.fence", host=host, reason=reason,
                                  incarnation=msg.get("incarnation"),
                                  current=self.fence.incarnation)
                    self._send(host, {"type": "fence", "reason": reason,
                                      "current": self.fence.incarnation})
                    return
                state["worker_alive"] = True
                state["worker_pid"] = msg.get("pid")
                state["spawned_ts"] = now
                state["progress_ts"] = now
                state["up_inc"] = msg.get("incarnation")
                state["last_exit"] = None
                flight.record("pod.worker_up", host=host,
                              pid=msg.get("pid"),
                              incarnation=msg.get("incarnation"),
                              quarantined=msg.get("quarantined"))
                self._info("host %d worker up (pid %s, incarnation %s)",
                           host, msg.get("pid"), msg.get("incarnation"))
            elif kind == "worker_exit":
                if state["up_inc"] is not None and \
                        msg.get("incarnation") is not None and \
                        msg.get("incarnation") != state["up_inc"]:
                    # a late exit report from a PREVIOUS life (the
                    # waiter thread can lag past kill->agree->respawn)
                    # must not clobber the live worker's state
                    flight.record("pod.stale_exit", host=host,
                                  incarnation=msg.get("incarnation"),
                                  current=state["up_inc"])
                    return
                state["worker_alive"] = False
                state["worker_pid"] = None
                exit_rec = {"rc": msg.get("rc"),
                            "kind": msg.get("kind"),
                            "signature": msg.get("signature"),
                            "incarnation": msg.get("incarnation"),
                            # a death during the coordinated kill is a
                            # consequence of OUR SIGTERM/SIGKILL, not
                            # an independent event — the round's valve
                            # weighting must ignore it
                            "during_kill": self.phase in
                            ("killing", "agreeing", "respawning"),
                            "ts": now}
                state["last_exit"] = exit_rec
                flight.record("pod.worker_exit", host=host,
                              rc=exit_rec["rc"],
                              exit_kind=exit_rec["kind"],
                              signature=exit_rec["signature"],
                              incarnation=exit_rec["incarnation"])
                self._info("host %d worker exit rc=%s (%s)", host,
                           msg.get("rc"), msg.get("kind"))
                if self.phase in ("killing", "agreeing", "respawning"):
                    self._round_exits.setdefault(host, exit_rec)
            elif kind == "manifests":
                state["manifests"] = msg.get("commits", {})

    # -------------------------------------------------------------- tick
    def _tick(self):
        now = time.time()
        with self._lock:
            phase = self.phase
        if self.spawn_agents:
            self._respawn_dead_agents()
        if phase == "gathering":
            with self._lock:
                ready = all(s["conn"] is not None
                            for s in self.hosts.values())
            if ready:
                self._info("all %d agents registered — starting "
                           "incarnation 0", self.n_hosts)
                self._spawn_all(agreed=None, rollback=False)
        elif phase == "running":
            trigger = self._detect_trigger(now)
            if trigger is not None:
                self._begin_restart(trigger, now)
        elif phase == "killing":
            self._tick_killing(now)
        elif phase == "agreeing":
            self._tick_agreeing(now)
        elif phase == "replicating":
            self._tick_replicating(now)
        elif phase == "respawning":
            self._tick_respawning(now)

    def _respawn_dead_agents(self):
        for host, proc in list(self._agent_procs.items()):
            if proc.poll() is not None and not self._stopping:
                with self._lock:
                    if self.phase in ("done", "giveup"):
                        return
                if os.path.exists(self.host_down_file(host)):
                    # the host is modeled GONE (chaos/tests): no agent
                    # can run there until the marker clears — exactly a
                    # dead machine's behavior on a real pod, where the
                    # master never spawns agents at all
                    continue
                # an agent that cannot even stay up (bad install,
                # unreachable master port) must not respawn forever
                recent = [t for t in self._agent_spawns.get(host, [])
                          if time.time() - t < 60]
                if len(recent) >= 5:
                    self._error("host %d agent died %d times in 60s "
                                "(rc=%s) — giving up the pod; see %s",
                                host, len(recent), proc.returncode,
                                os.path.join(self.host_workdir(host),
                                             "agent.log"))
                    flight.record("pod.giveup",
                                  reason="agent-crash-loop", host=host)
                    with self._lock:
                        self.phase = "giveup"
                        self.rc = 1
                    return
                self._info("host %d agent died (rc=%s) — respawning it",
                           host, proc.returncode)
                flight.record("pod.agent_died", host=host,
                              rc=proc.returncode)
                self._spawn_agent(host)

    def _detect_trigger(self, now):
        with self._lock:
            live = self.live_hosts()
            # capacity re-expansion: a LOST host's agent re-registered
            # — one coordinated restart back to full size (checked
            # first: the degraded pod is healthy, nothing else fires)
            if self.reexpand:
                # a block from a failed transfer expires after a
                # cooldown (the agent may never re-register if it
                # simply stayed connected) — a timestamped block
                # re-probes, an untimestamped one waits for agent_up
                cooldown = max(60.0, self.loss_window_s)
                for h, ts in list(self._reexpand_block_ts.items()):
                    if now - ts >= cooldown:
                        self._reexpand_block_ts.pop(h, None)
                        self._reexpand_blocked.discard(h)
                returned = [h for h in sorted(self.lost_hosts)
                            if h not in self._reexpand_blocked
                            and self.hosts[h]["conn"] is not None
                            and self.hosts[h]["conn"].alive]
                if returned:
                    return {"cause": "capacity-restore",
                            "hosts": returned}
            # pod-wide completion: every LIVE host's CURRENT-incarnation
            # worker exited 0 (a degraded pod completes on the
            # survivors — that is the point of continuing)
            exits = {h: self.hosts[h]["last_exit"] for h in live}
            if all(e is not None and e["kind"] == "done"
                   and e.get("incarnation") == self.fence.incarnation
                   for e in exits.values()):
                self._info("all %d live hosts finished cleanly — pod "
                           "done%s", len(live),
                           " (degraded, lost: %s)"
                           % sorted(self.lost_hosts)
                           if self.lost_hosts else "")
                flight.record("pod.done",
                              incarnation=self.fence.incarnation,
                              degraded=bool(self.lost_hosts),
                              lost_hosts=sorted(self.lost_hosts))
                self.phase = "done"
                self.rc = 0
                return None
            for h, e in sorted(exits.items()):
                if e is not None and e["kind"] != "done" and \
                        e.get("incarnation") == self.fence.incarnation:
                    return {"cause": "worker-exit", "host": h,
                            "exit": e}
            # lost hosts and hosts whose worker finished are excluded
            # from the stall view (their progress legitimately stopped)
            view = {h: {"heartbeat_ts": s["heartbeat_ts"],
                        "progress_ts": s["progress_ts"],
                        "worker_alive": s["worker_alive"]}
                    for h in live
                    for s in (self.hosts[h],)
                    if not (s["last_exit"] is not None
                            and s["last_exit"]["kind"] == "done")}
            stall = classify_stall(now, view, self.hang_seconds,
                                   self.stale_after_s)
        if stall is not None:
            return {"cause": stall["cause"], "hosts": stall["hosts"]}
        return None

    # ------------------------------------------------- coordinated restart
    def _begin_restart(self, trigger, now):
        with self._lock:
            self._round_cause = trigger
            self._round_started = now
            self._round_exits = {}
            for h, s in self.hosts.items():
                if trigger.get("host") == h and "exit" in trigger:
                    self._round_exits[h] = trigger["exit"]
                s.pop("manifests", None)
            self.phase = "killing"
        cause = trigger["cause"]
        if "exit" in trigger:
            cause = "%s:%s" % (cause, trigger["exit"]["kind"])
        self._info("pod restart: %s — killing every worker "
                   "(SIGTERM -> %.1fs -> SIGKILL)", cause,
                   self.kill_grace_s)
        flight.record("pod.stall" if trigger["cause"] in
                      ("stale-heartbeat", "collective-hang")
                      else "pod.trigger", **trigger)
        flight.record("pod.kill", cause=cause)
        with self._lock:
            for h in self.hosts:
                self._send(h, {"type": "kill_worker",
                               "grace_ms": int(self.kill_grace_s * 1e3)})

    def _tick_killing(self, now):
        with self._lock:
            # only hosts with a LIVE agent can confirm the kill — a
            # host whose agent is gone (permanent loss) would hold this
            # phase at its last heartbeat's stale worker_alive forever;
            # its orphan worker is the returning agent's fence problem
            alive = [h for h, s in self.hosts.items()
                     if s["worker_alive"] and s["conn"] is not None
                     and s["conn"].alive]
            timed_out = now - self._round_started > \
                self.kill_grace_s * 3 + 30
            if alive and not timed_out:
                return
            if alive:
                self._info("killing timed out with %s still reported "
                           "alive — proceeding (their agents will "
                           "fence them)", alive)
            self._round_started = now
            self.phase = "agreeing"
            for h in self.hosts:
                self._send(h, {"type": "report_manifests",
                               "prefix": self.prefix,
                               "snapshot_dir":
                                   self.host_snapshot_dir(h)})

    def _tick_agreeing(self, now):
        reexpanding = self._round_cause.get("cause") == \
            "capacity-restore"
        returned = sorted(self._round_cause.get("hosts", ())) \
            if reexpanding else []
        with self._lock:
            live = self.live_hosts()
            # only LIVE hosts gate the agreement; a returned (still
            # formally lost) host's report is advisory — it decides
            # whether the agreed commit must be replicated to it
            missing = [h for h in live
                       if "manifests" not in self.hosts[h]]
            absent = [h for h in missing
                      if self.hosts[h]["conn"] is None
                      or not self.hosts[h]["conn"].alive]
            # a host with NO agent is given the (shorter, configurable)
            # loss window — it is a permanent-loss candidate; a host
            # whose agent is merely slow keeps the full grace
            window = (self.loss_window_s
                      if absent and set(absent) == set(missing)
                      else max(60.0, self.loss_window_s))
            # the returned hosts' reports decide whether the agreed
            # commit must be REPLICATED to them — computing `need` off
            # a report that is merely in flight would ship (or cap-fail
            # on) a commit the host already holds valid, so they join
            # the window-bounded wait; they never gate the agreement
            # vote itself
            waiting = missing + [h for h in returned
                                 if "manifests" not in self.hosts[h]]
            if waiting and now - self._round_started < window:
                return
            reports = {h: s["manifests"] for h, s in self.hosts.items()
                       if "manifests" in s}
        # ---- permanent-loss strikes (the elastic tentpole) ----------
        # one strike per coordinated round in which a live host's agent
        # never re-registered within the window; ``loss_strikes``
        # consecutive misses classify the loss PERMANENT and the pod
        # degrades to the survivors instead of retrying the dead
        # topology until a valve gives up
        newly_lost = []
        for h in live:
            if h in absent:
                self.absence_strikes[h] += 1
                # a loss verdict needs somewhere to degrade TO: at
                # least one live host that is NOT itself absent (an
                # all-absent pod is a partition of the MASTER, not a
                # host loss — that stays the agreement-incomplete
                # giveup below, data intact)
                if self.elastic and \
                        self.absence_strikes[h] >= self.loss_strikes \
                        and len(live) > len(absent):
                    newly_lost.append(h)
            else:
                self.absence_strikes[h] = 0
        if newly_lost:
            with self._lock:
                self.lost_hosts.update(newly_lost)
                live = self.live_hosts()
            missing = [h for h in missing if h not in newly_lost]
            absent = [h for h in absent if h not in newly_lost]
            self._error(
                "host(s) %s classified PERMANENTLY lost (%d strike(s) "
                "each) — degrading the pod to survivors %s",
                newly_lost, self.loss_strikes, live)
            flight.record("pod.degrade", lost=newly_lost,
                          strikes=self.loss_strikes, live=live,
                          incarnation=self.fence.incarnation)
            self._export_pod_size()
        resize = ("degrade" if newly_lost
                  else "reexpand" if reexpanding else None)
        # agreement over the LIVE hosts' reports only: the lost hosts
        # no longer vote (their frozen rings must not veto the
        # survivors' newer commits), and a returned host votes again
        # only once it is re-expanded in
        reports = {h: r for h, r in reports.items() if h in live}
        from veles_tpu.services.snapshotter import (_commit_order_key,
                                                    agree_commits)
        agreed, detail = agree_commits(reports)
        forced = None
        if missing:
            # a host that never reported is UNKNOWN, not empty.
            # Agreement over the survivors alone may pick a commit the
            # silent host tore or lost — resuming from it would diverge
            # the pod the moment the host returns — and treating the
            # silent host as empty would drive agreed=None and
            # quarantine EVERY valid checkpoint pod-wide off a
            # transient partition.  Only a checkpoint that was
            # pod-verified on every host at an earlier agreement is
            # safe: fall back to it, or give up with the data intact.
            self._error("no manifest report from host(s) %s — "
                        "restricting agreement to pod-verified "
                        "checkpoints", missing)
            last = self._last_agreed
            if last is not None and reports and all(
                    r.get(last, {}).get("valid") is True
                    for r in reports.values()):
                agreed = last
            elif self.elastic and absent \
                    and set(absent) == set(missing) \
                    and len(live) > len(absent):
                # every silent host is agent-dead — a permanent-loss
                # candidate mid-strike — and there is no commit the
                # whole pod could provably restore.  A full-topology
                # respawn would hand the absent host a survivor-only
                # commit it may not hold (silent divergence when it
                # returns), and giving up would end a pod whose
                # survivors are healthy.  Recycle the round instead:
                # each recycle strikes the absent hosts toward the
                # permanent-loss verdict (degrade), or they return and
                # report — either way the pod decides with data intact.
                self._info("no pod-verified fallback while host(s) %s "
                           "are agent-dead — recycling the round "
                           "toward a permanent-loss verdict (strike "
                           "%s/%d)", absent,
                           {h: self.absence_strikes[h] for h in absent},
                           self.loss_strikes)
                self._begin_restart({"cause": "host-absent-retry",
                                     "hosts": absent}, now)
                return
            else:
                agreed = None
                forced = "agreement-incomplete"
        rejected = {n: d["rejected"] for n, d in detail.items()
                    if d["rejected"]}
        flight.record("pod.agree", agreed=agreed, rejected=rejected,
                      missing=missing or None,
                      incarnation=self.fence.incarnation)
        self._info("checkpoint agreement: %s%s", agreed or "none",
                   " (rejected: %s)" % rejected if rejected else "")
        # valves: did the agreed checkpoint advance since last restart?
        key = None
        if agreed is not None:
            entries = [r[agreed] for r in reports.values()
                       if agreed in r]
            key = _commit_order_key(agreed, entries)
        # the explicit quarantine set, from the CROSS-host ordering:
        # same-epoch commits tie-break on mtime and local clocks can
        # disagree, so the master decides once and every host
        # quarantines the same names (rollback_to_commit adds locally
        # invalid commits on top)
        if agreed is not None:
            quarantine = sorted(
                n for n in detail
                if n != agreed and _commit_order_key(
                    n, [r[n] for r in reports.values() if n in r]) > key)
        else:
            # no agreement: quarantine the rejected ring — EXCEPT
            # commits that are unverifiable EVERYWHERE they exist
            # (valid None on every host that has them: a manifestless
            # or foreign-backend ring, e.g. a workflow hard-coding the
            # orbax/db snapshotter past the forced file backend).
            # Renaming data the agreement cannot judge to *.corrupt
            # and resuming from scratch would silently destroy the
            # run — give up with the data intact instead.
            unverifiable = [
                n for n, d in detail.items()
                if all(reports[h][n].get("valid") is None
                       for h in d["hosts"])]
            quarantine = sorted(n for n in detail
                                if n not in unverifiable)
            if unverifiable and forced is None:
                self._error(
                    "no commit verifiable on any host (%s) — "
                    "unverifiable ring left intact, giving up",
                    sorted(unverifiable))
                forced = "agreement-unverifiable"
        progressed = key is not None and \
            (self._last_agreed_key is None or key > self._last_agreed_key)
        signatures = tuple(
            "%s=%s" % (h, e.get("signature"))
            for h, e in sorted(self._round_exits.items())
            if e.get("signature"))
        counted, flake = self._round_weight()
        if resize:
            # a planned topology change is the pod WORKING: its own
            # valve bucket, never the crash-loop window or the
            # deterministic-bug counter, and no backoff
            counted = False
        if flake and not progressed:
            self._flake_streak += 1
        else:
            self._flake_streak = 0
        # numeric-fault exits (the sentinel's rung-3 escalation) judge
        # their signature regardless of checkpoint progress: the
        # rollback replays COMMIT while diverging identically, and a
        # progressed-reset would crash-loop the pod on a deterministic
        # numeric bug forever
        sticky = any(
            str(e.get("kind") or "").startswith("numerics:")
            for e in self._round_exits.values())
        verdict = forced or self.valves.admit(now, signatures or None,
                                              progressed, counted,
                                              resize=bool(resize),
                                              sticky_signature=sticky)
        if verdict == "respawn" and \
                self._flake_streak >= self.flake_streak_limit:
            verdict = "env-flake-storm"
        cause = self._round_cause["cause"]
        if "exit" in self._round_cause:
            cause = "%s:%s" % (cause,
                               self._round_cause["exit"]["kind"])
        if newly_lost:
            cause = "host-loss:%s" % ",".join(map(str, newly_lost))
        record = {"cause": cause, "trigger": self._round_cause,
                  "exits": {h: dict(e) for h, e in
                            self._round_exits.items()},
                  "agreed": agreed, "rejected": rejected,
                  "progressed": progressed, "counted": counted,
                  "env_flake": flake, "verdict": verdict,
                  "resize": resize, "lost": sorted(self.lost_hosts),
                  "incarnation_before": self.fence.incarnation,
                  "ts": now}
        if verdict != "respawn":
            self._error("pod giving up: %s (restarts=%d)", verdict,
                        len(self.history))
            flight.record("pod.giveup", reason=verdict, cause=cause)
            flight.dump(directory=os.path.join(self.workdir, "dumps"),
                        reason="pod-giveup")
            with self._lock:
                self.history.append(record)
                self.restart_causes.append(cause)
                self.phase = "giveup"
                rcs = [e.get("rc") for e in
                       self._round_exits.values() if e.get("rc")]
                self.rc = rcs[0] if rcs else 1
            return
        if progressed:
            self._consecutive = 0
        self._consecutive += 1
        delay = 0.0 if not counted else backoff_delay(
            self._consecutive, self.backoff_base, self.backoff_max,
            self._rng)
        self._last_agreed = agreed
        if key is not None:
            self._last_agreed_key = key
        with self._lock:
            self.history.append(record)
            self.restart_causes.append(cause)
        targets = self.live_hosts()
        if reexpanding:
            targets = sorted(set(targets) | set(returned))
            with self._lock:
                # a returned host whose own ring already holds the
                # agreed commit VALID (shared storage, short absences)
                # needs no transfer; otherwise the commit is shipped
                # over the control plane from a survivor that has it
                need = [h for h in returned
                        if agreed is not None and
                        (self.hosts[h].get("manifests") or {})
                        .get(agreed, {}).get("valid") is not True]
                src = None
                if agreed is not None:
                    src = next(
                        (h for h in self.live_hosts()
                         if (self.hosts[h].get("manifests") or {})
                         .get(agreed, {}).get("valid") is True), None)
            if need and src is not None:
                self._begin_replication(src, need, returned, agreed,
                                        quarantine, targets, now)
                return
            if need:
                # nothing to replicate FROM (agreed absent) — re-expand
                # anyway; the returning host quarantines per the master
                # list and ``--snapshot auto``'s fallback covers it
                self._error("no survivor holds the agreed commit to "
                            "replicate — re-expanding without transfer")
            self._complete_reexpand(returned)
        if delay:
            self._info("respawn backoff %.2fs", delay)
            time.sleep(delay)
        self._spawn_all(agreed=agreed, rollback=True,
                        quarantine=quarantine, hosts=targets)

    # ------------------------------------------- re-expand & replication
    def _complete_reexpand(self, returned):
        """Fold the returned hosts back into the live set — capacity
        restored, one re-expand restart (the caller spawns it)."""
        with self._lock:
            for h in returned:
                self.lost_hosts.discard(h)
                self.absence_strikes[h] = 0
                self._reexpand_blocked.discard(h)
                self._reexpand_block_ts.pop(h, None)
            live = self.live_hosts()
        flight.record("pod.restore", hosts=list(returned), live=live,
                      incarnation=self.fence.incarnation)
        self._info("capacity restored: host(s) %s rejoin — "
                   "re-expanding the pod to %d host(s)",
                   list(returned), len(live))
        self._export_pod_size()

    def _begin_replication(self, src, need, returned, agreed,
                           quarantine, targets, now):
        """Ship the agreed commit (data file + manifest sidecar) from a
        survivor to the returning host(s) over the control plane — the
        returning ring is frozen at the loss point and, on per-host
        disks, has no other way to reach the degraded era's newer
        commits."""
        with self._lock:
            self.phase = "replicating"
            self._round_started = now
            if self.history:
                self.history[-1]["replicated"] = list(need)
            self._replication = {
                "source": src, "need": list(need),
                "returned": list(returned), "agreed": agreed,
                "quarantine": quarantine, "targets": targets,
                "files": None, "sent": False, "pushed": set(),
                "failed": [], "error": None}
        flight.record("pod.replicate", source=src, to=list(need),
                      name=agreed)
        self._info("replicating agreed commit %s from host %d to "
                   "host(s) %s for re-expansion", agreed, src, need)
        with self._lock:
            if not self._send(src, {
                    "type": "fetch_commit", "name": agreed,
                    "snapshot_dir": self.host_snapshot_dir(src),
                    "max_mb": self.replicate_max_mb}):
                self._replication["error"] = "source agent unreachable"

    def _tick_replicating(self, now):
        with self._lock:
            rep = self._replication
            if rep is None:            # defensive: lost context
                self.phase = "running"
                return
            if rep["files"] is not None and not rep["sent"]:
                rep["sent"] = True
                for h in rep["need"]:
                    if not self._send(h, {
                            "type": "push_commit",
                            "snapshot_dir": self.host_snapshot_dir(h),
                            "files": rep["files"]}):
                        rep["failed"].append(h)
            done = set(rep["pushed"]) >= set(rep["need"])
            trouble = rep["error"] or rep["failed"]
            timed_out = now - self._round_started > \
                max(120.0, self.kill_grace_s * 3)
        if done and not trouble:
            self._replication = None
            self._complete_reexpand(rep["returned"])
            self._spawn_all(agreed=rep["agreed"], rollback=True,
                            quarantine=rep["quarantine"],
                            hosts=rep["targets"])
            return
        if trouble or timed_out:
            # a failed transfer must not take the pod down OR wedge it:
            # stay degraded on the survivors and block re-expansion
            # until the host's agent re-registers OR the cooldown in
            # _detect_trigger expires (whichever comes first retries)
            reason = rep["error"] or (
                "push failed on %s" % rep["failed"]) if trouble \
                else "replication timed out"
            self._error("re-expansion aborted (%s) — staying degraded",
                        reason)
            flight.record("pod.reexpand_failed", reason=reason,
                          hosts=rep["returned"])
            with self._lock:
                self._reexpand_blocked.update(rep["returned"])
                for h in rep["returned"]:
                    self._reexpand_block_ts[h] = now
            self._replication = None
            self._spawn_all(agreed=rep["agreed"], rollback=True,
                            quarantine=rep["quarantine"],
                            hosts=self.live_hosts())

    def _round_weight(self):
        """(counted, env_flake) for the round's valve decision: a pod
        whose every INDEPENDENT death this round was a graceful
        preemption — or the sandbox startup flake — respawns uncounted
        (flakes bounded by the streak valve in ``_tick_agreeing``).
        Exits from the coordinated kill itself (``during_kill``) are
        consequences, not causes — excluded from the weighting."""
        if self._round_cause.get("cause") in ("capacity-restore",
                                              "host-absent-retry"):
            # planned resize probing: capacity return is healthy, and
            # the absent-retry recycle is the strike accumulator for a
            # dead host — neither is a failure of the POD, so neither
            # may consume the crash-loop budget (the strike/loss valves
            # bound them)
            return False, False
        exits = [e for e in self._round_exits.values()
                 if not e.get("during_kill")]
        kinds = {e.get("kind") for e in exits}
        flake = bool(exits) and kinds <= {"env-flake", "preempt", "done"}
        preempt_only = bool(exits) and kinds <= {"preempt", "done"}
        cause = self._round_cause.get("cause")
        counted = not (cause == "worker-exit" and (flake or preempt_only))
        return counted, flake and not preempt_only

    def _spawn_all(self, agreed, rollback, quarantine=None, hosts=None):
        # the first spawn keeps incarnation 0; every coordinated
        # restart fences a new life
        hosts = sorted(hosts) if hosts is not None else \
            self.live_hosts()
        incarnation = self.fence.bump() if rollback \
            else self.fence.incarnation
        coord_port = _free_port(self.coordinator_host)
        with self._lock:
            self.phase = "respawning"
            self._round_started = time.time()
            self._spawn_targets = set(hosts)
            self._pending_specs = {}
            for h, s in self.hosts.items():
                s["last_exit"] = None
                s["worker_alive"] = False
                s["up_inc"] = None
        flight.record("pod.respawn", incarnation=incarnation,
                      agreed=agreed, coordinator_port=coord_port,
                      hosts=hosts, degraded=len(hosts) < self.n_hosts)
        self._info("spawning incarnation %d on host(s) %s "
                   "(coordinator %s:%d%s%s)",
                   incarnation, hosts, self.coordinator_host,
                   coord_port,
                   ", resume from %s" % agreed if agreed else "",
                   ", DEGRADED %d/%d" % (len(hosts), self.n_hosts)
                   if len(hosts) < self.n_hosts else "")
        with self._lock:
            for h in hosts:
                spec = self.worker_spec(
                    h, incarnation, coord_port, agreed=agreed,
                    rollback=rollback, quarantine=quarantine,
                    live=hosts)
                if not self._send(h, spec):
                    # agent not (yet) registered: deliver the spec if
                    # it registers while this incarnation is current —
                    # the full-topology retry rounds depend on it
                    self._pending_specs[h] = spec

    def _tick_respawning(self, now):
        with self._lock:
            pending = [h for h in sorted(self._spawn_targets)
                       if self.hosts[h]["up_inc"]
                       != self.fence.incarnation]
            if not pending:
                self.phase = "running"
                return
            absent = [h for h in pending
                      if self.hosts[h]["conn"] is None
                      or not self.hosts[h]["conn"].alive]
        if self.elastic and absent \
                and now - self._round_started > self.loss_window_s:
            # the spawned survivors are blocked inside
            # jax.distributed.initialize waiting for a host that never
            # came back — recycle the round (uncounted) so the absence
            # strikes accumulate toward the permanent-loss verdict
            # instead of burning the 300 s respawn timeout into giveup
            self._info("host(s) %s still absent %.0fs into the "
                       "respawn — recycling the round toward a "
                       "permanent-loss verdict", absent,
                       now - self._round_started)
            self._begin_restart({"cause": "host-absent-retry",
                                 "hosts": absent}, now)
            return
        if now - self._round_started > 300:
            self._error("workers of incarnation %d never came up on "
                        "host(s) %s — giving up",
                        self.fence.incarnation, pending)
            flight.record("pod.giveup", reason="respawn-timeout",
                          hosts=pending)
            with self._lock:
                self.phase = "giveup"
                self.rc = 1

    # ----------------------------------------------------------- shutdown
    def _shutdown_agents(self):
        with self._lock:
            for h in self.hosts:
                self._send(h, {"type": "shutdown"})
        deadline = time.time() + self.kill_grace_s + 10
        for host, proc in self._agent_procs.items():
            while proc.poll() is None and time.time() < deadline:
                time.sleep(0.05)
            if proc.poll() is None:
                try:
                    proc.terminate()
                    proc.wait(timeout=5)
                except (OSError, subprocess.TimeoutExpired):
                    try:
                        proc.kill()
                    except OSError:
                        pass

    def _info(self, msg, *args):
        self._log.info(msg, *args)
        print("[pod] " + msg % args, file=sys.stderr, flush=True)

    def _error(self, msg, *args):
        self._log.error(msg, *args)
        print("[pod] " + msg % args, file=sys.stderr, flush=True)


# =====================================================================
# the serving-fleet master (the pod master owning the SERVING plane)
# =====================================================================

class ServeFleetMaster(object):
    """The pod master's serving plane: own ``min..max`` engine
    replicas across ``n_hosts`` per-host agents, behind an in-process
    :class:`~veles_tpu.services.router.FleetRouter`
    (docs/services.md "Autoscaling fleet"; ``veles-tpu-pod --serve``).

    The declarative fleet spec (``root.common.serve.fleet.{min,max,
    per_host}``) drives per-host spawn/drain over the same line-JSON
    control plane the training pod uses: agents spawn the replica
    command (any process that prints the ``REPLICA_READY port=...``
    handshake — ``--serve`` workflows do under an agent), the master
    auto-registers each announced port with its router and
    deregisters it on death/drain.  A replica lost to host death or
    process crash is classified with the shared supervisor taxonomy
    (``classify_exit`` / the env-flake fingerprint) and replaced on a
    surviving host within the PR 10 strike-ladder semantics: the
    router's health probe detects the death within one interval, the
    ``fleet.replace`` flight event records the verdict, host-death
    replacements ride the resize valve bucket (planned recovery,
    never the crash-loop budget), and replica ids are fenced —
    monotonic, never reused, so a zombie replica's late READY cannot
    re-register (it is ordered killed instead).

    The autoscaler loop closes the measured feedback loop: the SLO
    shedder's queue-wait overshoot and fresh ``serve.shed``
    rejections (aggregated by :meth:`FleetRouter.fleet_signals` off
    the health probes) scale the fleet up; sustained idle scales it
    down — always through the SIGTERM drain, so scale-down is
    lossless by construction.  Every decision passes
    :meth:`PodValves.admit_scale` (flap damping in its own bucket).
    Gate: ``tools/fleet_chaos.py``."""

    def __init__(self, replica_argv, n_hosts=1, fleet_min=None,
                 fleet_max=None, per_host=None, workdir=None, port=0,
                 bind_host="127.0.0.1", router_port=0,
                 replica_path="/service", env=None, spawn_agents=True,
                 heartbeat_ms=None, stale_after_ms=None,
                 health_interval_ms=None, kill_grace_ms=None,
                 max_restarts=None, window_seconds=None,
                 deterministic_limit=None, loss_strikes=None,
                 loss_window_s=None, scale_up_overshoot=None,
                 scale_idle_s=None, scale_cooldown_s=None,
                 scale_window_s=None, scale_max_per_window=None,
                 ready_timeout_ms=None, min_uptime_s=None,
                 autoscale=True, autoscale_interval_s=0.5,
                 host_extras=None, seed=None, prefill_replicas=None,
                 prefill_prompt_min=None, prefill_handoff_new=None,
                 scale_up_prefill_backlog=None, placement=None):
        from veles_tpu.services.router import FleetRouter

        def fknob(value, key, default):
            if value is not None:
                return value
            return root.common.serve.fleet.get(key, default)

        def pknob(value, key, default):
            if value is not None:
                return value
            return root.common.pod.get(key, default)

        self.replica_argv = list(replica_argv)
        self.n_hosts = int(n_hosts)
        self.workdir = os.path.abspath(workdir or "fleet-workdir")
        self.fleet_min = int(fknob(fleet_min, "min", 1))
        self.fleet_max = max(int(fknob(fleet_max, "max", 8)),
                             self.fleet_min)
        self.per_host = int(fknob(per_host, "per_host", 2))
        #: prefill/decode fleet roles (docs/services.md "Disaggregated
        #: prefill"): this many of the desired replicas run as
        #: PREFILL-role — the router sends long prompts' admission
        #: prefill there first and splices the decode onto a
        #: decode-role replica.  A dead prefill replica's replacement
        #: inherits the deficit (role rebalance is reconciliation).
        self.prefill_replicas = max(0, int(
            fknob(prefill_replicas, "prefill_replicas", 0)))
        self.replica_path = replica_path
        self.port = int(port)
        self.bind_host = bind_host
        self.env = env
        self.spawn_agents = bool(spawn_agents)
        self.host_extras = dict(host_extras or {})
        self.heartbeat_s = float(
            pknob(heartbeat_ms, "heartbeat_ms", 500)) / 1e3
        self.stale_after_s = float(
            pknob(stale_after_ms, "stale_after_ms", 10000)) / 1e3
        self.kill_grace_s = float(
            pknob(kill_grace_ms, "kill_grace_ms", 5000)) / 1e3
        self.loss_strikes = int(pknob(loss_strikes, "loss_strikes", 2))
        self.loss_window_s = float(
            pknob(loss_window_s, "loss_window_s", 60))
        self.ready_timeout_s = float(
            fknob(ready_timeout_ms, "ready_timeout_ms", 180000)) / 1e3
        self.min_uptime_s = float(
            fknob(min_uptime_s, "min_uptime_s", 30.0))
        self.autoscale = bool(autoscale)
        self.autoscale_interval_s = float(autoscale_interval_s)
        self.valves = PodValves(
            pknob(max_restarts, "max_restarts", 8),
            pknob(window_seconds, "window_seconds", 600),
            pknob(deterministic_limit, "deterministic_limit", 3),
            scale_max_per_window=fknob(scale_max_per_window,
                                       "scale_max_per_window", 4),
            scale_window_seconds=fknob(scale_window_s,
                                       "scale_window_s", 120.0))
        self.autoscaler = FleetAutoscaler(
            up_overshoot=fknob(scale_up_overshoot,
                               "scale_up_overshoot", 1.0),
            idle_s=fknob(scale_idle_s, "scale_idle_s", 30.0),
            cooldown_s=fknob(scale_cooldown_s, "scale_cooldown_s",
                             10.0),
            up_prefill_backlog=fknob(scale_up_prefill_backlog,
                                     "scale_up_prefill_backlog",
                                     4096))
        self.router = FleetRouter(
            port=router_port,
            health_interval_ms=health_interval_ms,
            placement=placement,
            prefill_prompt_min=prefill_prompt_min,
            prefill_handoff_new=prefill_handoff_new)
        self._rng = random.Random(seed)
        self._log = logging.getLogger("ServeFleet")
        self._lock = threading.Lock()
        # lint-ok: VT804 — control-plane inbox: producers are the
        # per-replica reader threads (bounded by fleet size), the
        # policy loop drains every cycle, and lifecycle events must
        # never be dropped or block the readers
        self._inbox = queue.Queue()
        self._listener = None
        self._threads = []
        self._agent_procs = {}
        self._agent_spawns = {}
        self._stopping = False
        self.phase = "gathering"
        self.rc = None
        self.desired = self.fleet_min
        self.hosts = {h: {"conn": None, "addr": "127.0.0.1",
                          "registered_ts": None, "heartbeat_ts": None,
                          "down_since": time.time()}
                      for h in range(self.n_hosts)}
        self.lost_hosts = set()
        #: rep_id -> {"host", "state": spawning|ready|dying|draining|
        #: dead, "rid", "port", "pid", "spawn_ts", "ready_ts",
        #: "exit"} — rep ids are MONOTONIC and never reused (the
        #: replica fence: a late READY under a retired id is refused)
        self.reps = {}
        self._next_rep = 0
        self.replaced_total = 0
        #: one record per scale decision / replacement / drain
        self.history = []
        self.drained = []
        #: a crash-loop / deterministic-bug valve verdict holds all
        #: further REPLACEMENT spawns (the fleet keeps serving on what
        #: is left — a crashing replica binary must not respawn
        #: forever, but taking the survivors down would be worse)
        self.hold_replace = None
        self._last_autoscale = 0.0
        self._last_note = 0.0
        self._started_ts = None
        #: set AFTER the policy loop's teardown (agents shut down,
        #: router stopped) — wait() blocks on this instead of joining
        #: the thread: a KeyboardInterrupt-interrupted join can poison
        #: the thread's tstate lock in CPython, making a later
        #: join/is_alive misreport a live thread as finished
        self._finished = threading.Event()

    # ------------------------------------------------------------ layout
    def host_workdir(self, host):
        return os.path.join(self.workdir, "agent%d" % host)

    def host_down_file(self, host):
        """Same GONE-machine marker the training pod master uses (see
        :meth:`PodMaster.host_down_file`) — the chaos harness's model
        of a dead host."""
        return os.path.join(self.workdir, "host%d.down" % host)

    def agent_argv(self, host):
        return [sys.executable, "-m", "veles_tpu.services.podmaster",
                "--agent", "--master",
                "%s:%d" % (self.bind_host, self.port),
                "--host-id", str(host),
                "--workdir", self.host_workdir(host)]

    def live_hosts(self):
        """Hosts a replica may be PLACED on right now: agent
        connected, heartbeat fresh, not classified lost."""
        now = time.time()
        out = []
        for h, s in sorted(self.hosts.items()):
            if h in self.lost_hosts:
                continue
            if s["conn"] is None or not s["conn"].alive:
                continue
            if s["heartbeat_ts"] is not None \
                    and now - s["heartbeat_ts"] > self.stale_after_s:
                continue
            out.append(h)
        return out

    # --------------------------------------------------------- lifecycle
    def start(self):
        os.makedirs(self.workdir, exist_ok=True)
        self._started_ts = time.time()
        self.router.start()
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((self.bind_host, self.port))
        self.port = self._listener.getsockname()[1]
        self._listener.listen(self.n_hosts + 4)
        t = threading.Thread(target=self._accept_loop,
                             name="FleetAccept", daemon=True)
        t.start()
        self._threads.append(t)
        if self.spawn_agents:
            for h in range(self.n_hosts):
                self._spawn_agent(h)
        else:
            for h in range(self.n_hosts):
                print("[fleet] host %d agent command: %s"
                      % (h, " ".join(self.agent_argv(h))), flush=True)
        self._policy_thread = threading.Thread(
            target=self._policy_loop, name="FleetPolicy", daemon=True)
        self._policy_thread.start()
        self._info("fleet master on %s:%d — router http://%s:%d%s, "
                   "spec min=%d max=%d per_host=%d over %d host(s)",
                   self.bind_host, self.port, self.router.host,
                   self.router.port, self.router.path, self.fleet_min,
                   self.fleet_max, self.per_host, self.n_hosts)
        return self

    def wait(self, timeout=None):
        """Block until the fleet finishes/gives up (the final rc), or
        ``timeout`` passes (None)."""
        if not self._finished.wait(timeout):
            return None
        return self.rc

    def run(self):
        self.start()
        return self.wait()

    def stop(self, rc=0):
        """Graceful shutdown: drain every replica (agents SIGTERM
        them), stop the agents and the router."""
        with self._lock:
            if self.phase in ("done", "giveup"):
                return
            self._stopping = True
        self._inbox.put(("stop", None, {"rc": rc}))

    def status(self):
        with self._lock:
            live = [r for r in self.reps.values()
                    if r["state"] == "ready"]
            return {
                "phase": self.phase,
                "desired": self.desired,
                "spec": {"min": self.fleet_min, "max": self.fleet_max,
                         "per_host": self.per_host},
                "live_replicas": len(live),
                "replicas": {
                    rep: {"host": r["host"], "state": r["state"],
                          "port": r["port"], "pid": r["pid"],
                          "rid": r["rid"], "role": r.get("role")}
                    for rep, r in sorted(self.reps.items())
                    if r["state"] != "dead"},
                "prefill_replicas": self.prefill_replicas,
                "hosts": {
                    h: {"registered": s["conn"] is not None
                        and s["conn"].alive,
                        "lost": h in self.lost_hosts}
                    for h, s in self.hosts.items()},
                "lost_hosts": sorted(self.lost_hosts),
                "replaced_total": self.replaced_total,
                "scale_events": self.valves.scale_events,
                "scale_damped": self.valves.scale_damped,
                "resize_restarts": self.valves.resize_restarts,
                "hold_replace": self.hold_replace,
                "router": {"host": self.router.host,
                           "port": self.router.port,
                           "path": self.router.path},
                "drained": list(self.drained),
            }

    # --------------------------------------------------- agent processes
    def _spawn_agent(self, host):
        os.makedirs(self.host_workdir(host), exist_ok=True)
        env = dict(self.env if self.env is not None else os.environ)
        import veles_tpu
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(veles_tpu.__file__)))
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else "")
        log = open(os.path.join(self.host_workdir(host), "agent.log"),
                   "ab")
        try:
            proc = subprocess.Popen(self.agent_argv(host), env=env,
                                    stdout=log, stderr=log)
        finally:
            log.close()
        self._agent_procs[host] = proc
        self._agent_spawns.setdefault(host, []).append(time.time())
        flight.record("fleet.agent_spawn", host=host, pid=proc.pid)

    def _respawn_dead_agents(self):
        for host, proc in list(self._agent_procs.items()):
            if proc.poll() is not None and not self._stopping:
                with self._lock:
                    if self.phase in ("done", "giveup"):
                        return
                if os.path.exists(self.host_down_file(host)):
                    continue        # machine modeled GONE (chaos)
                recent = [t for t in self._agent_spawns.get(host, [])
                          if time.time() - t < 60]
                if len(recent) >= 5:
                    self._error("host %d agent died %d times in 60s — "
                                "marking the host lost", host,
                                len(recent))
                    flight.record("fleet.host_lost", host=host,
                                  reason="agent-crash-loop")
                    with self._lock:
                        self.lost_hosts.add(host)
                    continue
                flight.record("fleet.agent_died", host=host,
                              rc=proc.returncode)
                self._spawn_agent(host)

    # ------------------------------------------------------ accept/reader
    def _accept_loop(self):
        while True:
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            conn = _Conn(sock)
            threading.Thread(target=self._reader, args=(conn,),
                             name="FleetReader", daemon=True).start()

    def _reader(self, conn):
        msg = conn.recv()
        if not msg or msg.get("type") != "register":
            conn.send({"type": "refused", "reason": "register-first"})
            conn.close()
            return
        host = msg.get("host")
        reason = None
        with self._lock:
            if not isinstance(host, int) or host not in self.hosts:
                reason = "unknown-host"
            elif self.hosts[host]["conn"] is not None \
                    and self.hosts[host]["conn"].alive:
                reason = "duplicate-host"
            else:
                self.hosts[host]["conn"] = conn
                self.hosts[host]["registered_ts"] = time.time()
                self.hosts[host]["heartbeat_ts"] = time.time()
                self.hosts[host]["down_since"] = None
                try:
                    self.hosts[host]["addr"] = \
                        conn.sock.getpeername()[0]
                except OSError:
                    pass
        if reason is not None:
            conn.send({"type": "refused", "reason": reason})
            conn.close()
            return
        conn.send({"type": "welcome",
                   "heartbeat_ms": int(self.heartbeat_s * 1e3)})
        flight.record("fleet.agent_up", host=host)
        self._inbox.put(("agent_up", host, msg))
        while True:
            msg = conn.recv()
            if msg is None:
                break
            self._inbox.put((msg.get("type", "garbage"), host, msg))
        conn.close()
        self._inbox.put(("agent_lost", host, {}))

    def _send(self, host, obj):
        conn = self.hosts[host]["conn"]
        return conn is not None and conn.send(obj)

    # -------------------------------------------------------- policy loop
    def _policy_loop(self):
        try:
            self._policy_loop_inner()
        except Exception as e:   # noqa: BLE001 — never die silently
            self._error("fleet policy loop crashed: %s: %s",
                        type(e).__name__, e)
            flight.record("fleet.policy_error", error=str(e))
            flight.dump(reason="fleet-policy-error", error=e)
            with self._lock:
                self.phase = "giveup"
                self.rc = 1
        finally:
            self._shutdown()
            self._finished.set()

    def _policy_loop_inner(self):
        while True:
            try:
                ev = self._inbox.get(timeout=0.05)
            except queue.Empty:
                ev = None
            if ev is not None:
                self._handle_event(*ev)
            self._tick(time.time())
            with self._lock:
                if self.phase in ("done", "giveup"):
                    return

    def _handle_event(self, kind, host, msg):
        now = time.time()
        if kind == "stop":
            with self._lock:
                self.phase = "stopping"
                self.rc = msg.get("rc", 0)
            self._begin_shutdown_drain()
            return
        if host is None:
            return
        with self._lock:
            state = self.hosts[host]
            if kind == "agent_up":
                if host in self.lost_hosts:
                    self.lost_hosts.discard(host)
                    flight.record("fleet.host_restored", host=host)
                    self._info("host %d agent re-registered — back "
                               "in the placement pool", host)
            elif kind == "agent_lost":
                state["conn"] = None
                state["heartbeat_ts"] = None
                state["down_since"] = now
                flight.record("fleet.agent_lost", host=host)
            elif kind == "heartbeat":
                state["heartbeat_ts"] = now
        if kind == "replica_up":
            self._handle_replica_up(host, msg, now)
        elif kind == "replica_exit":
            self._handle_replica_exit(host, msg, now)

    def _handle_replica_up(self, host, msg, now):
        rep = msg.get("rep")
        with self._lock:
            rec = self.reps.get(rep)
            fenced = (rec is None or rec["host"] != host
                      or rec["state"] != "spawning")
            if not fenced:
                rec["state"] = "ready"
                rec["ready_ts"] = now
                rec["port"] = msg.get("port")
                rec["pid"] = msg.get("pid")
                role = rec.get("role")
                addr = self.hosts[host]["addr"]
        if fenced:
            # the replica fence: rep ids are never reused, so a READY
            # from a replaced/retired/unknown id is a zombie — it must
            # not (re-)register with the router; order it killed
            flight.record("fleet.fence", host=host, rep=rep,
                          state=None if rec is None else rec["state"])
            self._info("fencing zombie replica %s on host %d", rep,
                       host)
            with self._lock:
                self._send(host, {"type": "kill_replica", "rep": rep})
            return
        url = "http://%s:%d%s" % (addr, msg["port"], self.replica_path)
        rid = self.router.register(url, role=role)
        with self._lock:
            rec = self.reps.get(rep)
            if rec is not None:
                rec["rid"] = rid
        flight.record("fleet.replica_ready", host=host, rep=rep,
                      rid=rid, url=url)
        self._info("replica %d ready on host %d (%s) — registered as "
                   "router replica %d", rep, host, url, rid)

    def _handle_replica_exit(self, host, msg, now):
        rep = msg.get("rep")
        with self._lock:
            rec = self.reps.get(rep)
            if rec is None or rec["state"] == "dead":
                return           # late report for a handled death
            prev_state = rec["state"]
            rec["state"] = "dead"
            rec["exit"] = {"rc": msg.get("rc"),
                           "kind": msg.get("kind"),
                           "signature": msg.get("signature")}
            rid = rec["rid"]
        if rid is not None:
            self.router.deregister(rid, reason="replica exit (%s)"
                                   % msg.get("kind"))
        if prev_state == "draining":
            # a planned scale-down (or shutdown) drain completing —
            # exit 0 (kind "done") is the lossless-by-construction
            # proof the chaos gate checks.  was_ready distinguishes a
            # drained SERVING replica (must exit 0) from a surplus
            # spawn stopped before it ever served (nothing to lose)
            entry = {"rep": rep, "host": host, "rc": msg.get("rc"),
                     "kind": msg.get("kind"),
                     "was_ready":
                         self.reps[rep].get("ready_ts") is not None,
                     "ts": now}
            with self._lock:
                self.drained.append(entry)
            flight.record("fleet.drained", rep=rep, host=host,
                          rc=entry["rc"], exit_kind=entry["kind"],
                          was_ready=entry["was_ready"])
            self._info("replica %d drained (rc=%s)", rep,
                       msg.get("rc"))
            return
        if self._stopping:
            return
        # unplanned death: the supervisor taxonomy decides the
        # replacement budget — env flakes/preempts respawn uncounted,
        # crashes are bounded by the crash-loop and deterministic-bug
        # valves (a replica binary that dies identically over and
        # over must not burn the fleet's budget forever)
        kind = msg.get("kind") or "crash:unknown"
        # an UNPLANNED clean exit is not clean for a serving replica
        # (they serve until drained): it counts like a crash, with a
        # stable signature, so a misconfigured replica command that
        # prints usage and exits 0 trips the deterministic-bug valve
        # instead of respawning unbudgeted forever
        counted = kind not in ("env-flake", "preempt")
        ready_ts = self.reps[rep].get("ready_ts")
        progressed = (prev_state in ("ready", "dying")
                      and ready_ts is not None
                      and now - ready_ts >= self.min_uptime_s)
        signature = msg.get("signature")
        if kind == "done" and signature is None:
            signature = "clean-exit"
        verdict = self.valves.admit(
            now, (str(signature),) if signature else None,
            progressed=progressed, counted=counted)
        record = {"action": "replace", "rep": rep, "host": host,
                  "cause": kind, "counted": counted,
                  "verdict": verdict, "ts": now}
        with self._lock:
            self.history.append(record)
        if verdict != "respawn":
            self._error("replica replacement held: %s (replica %d "
                        "died %s) — serving on the survivors",
                        verdict, rep, kind)
            flight.record("fleet.giveup", reason=verdict, rep=rep,
                          cause=kind)
            with self._lock:
                self.hold_replace = verdict
            return
        self.replaced_total += 1
        self.router.fleet_event("replace")
        flight.record("fleet.replace", rep=rep, host=host, cause=kind,
                      counted=counted)
        self._info("replica %d died (%s) — replacing", rep, kind)
        # the reconcile tick performs the actual replacement spawn

    def _handle_host_death_replicas(self, now):
        """Replicas whose router probe says DOWN while their agent is
        gone died with their machine: no ``replica_exit`` will ever
        arrive — deregister and replace them NOW (detection ≤ one
        health interval), in the resize bucket (planned recovery,
        PR 10 semantics: a host death is the pod doing its job, not a
        crash-looping binary)."""
        router_states = {rid: d["state"] for rid, d
                         in self.router.replicas().items()}
        with self._lock:
            agent_alive = {h: bool(s["conn"] is not None
                                   and s["conn"].alive
                                   and (s["heartbeat_ts"] is None
                                        or now - s["heartbeat_ts"]
                                        <= self.stale_after_s))
                           for h, s in self.hosts.items()}
            view = {rep: {"host": r["host"], "state": r["state"],
                          "rid": r["rid"]}
                    for rep, r in self.reps.items()}
        for rep, cause in dead_replica_verdicts(view, router_states,
                                                agent_alive):
            if cause == "down":
                # the agent is alive: kill the wedged process — its
                # replica_exit does the (counted) accounting
                with self._lock:
                    rec = self.reps.get(rep)
                    if rec is not None and rec["state"] == "ready":
                        rec["state"] = "dying"
                        self._send(rec["host"],
                                   {"type": "kill_replica",
                                    "rep": rep})
                continue
            with self._lock:
                rec = self.reps.get(rep)
                if rec is None or rec["state"] == "dead":
                    continue
                rec["state"] = "dead"
                rec["exit"] = {"rc": None, "kind": "host-death",
                               "signature": None}
                rid = rec["rid"]
                host = rec["host"]
            if rid is not None:
                self.router.deregister(rid, reason="host death")
            self.valves.admit(now, resize=True)
            self.replaced_total += 1
            self.router.fleet_event("replace")
            record = {"action": "replace", "rep": rep, "host": host,
                      "cause": "host-death", "counted": False,
                      "verdict": "respawn", "ts": now}
            with self._lock:
                self.history.append(record)
            flight.record("fleet.replace", rep=rep, host=host,
                          cause="host-death", counted=False)
            self._error("replica %d lost with host %d — replacing on "
                        "a survivor", rep, host)
        self._reap_lost_host_replicas(now)

    def _reap_lost_host_replicas(self, now):
        """Non-READY replicas stranded on a LOST host (spawning /
        dying / draining when the machine died) get no router-down
        verdict and no ``replica_exit`` ever — once the strike ladder
        declares the host lost, reap them here so they cannot hold a
        phantom slot (or block shutdown/scale-down waits) forever."""
        with self._lock:
            stranded = [(rep, r) for rep, r in self.reps.items()
                        if r["state"] in ("spawning", "dying",
                                          "draining")
                        and r["host"] in self.lost_hosts]
            for rep, r in stranded:
                prev, r["state"] = r["state"], "dead"
                r["exit"] = {"rc": None, "kind": "host-death",
                             "signature": None}
                r["prev_state"] = prev
        for rep, r in stranded:
            if r["rid"] is not None:
                self.router.deregister(r["rid"], reason="host death")
            prev = r.pop("prev_state")
            if prev == "draining":
                # the drain's outcome died with the machine — record
                # it honestly (kind host-death, no rc) rather than as
                # a clean drain
                entry = {"rep": rep, "host": r["host"], "rc": None,
                         "kind": "host-death",
                         "was_ready": r.get("ready_ts") is not None,
                         "ts": now}
                with self._lock:
                    self.drained.append(entry)
                flight.record("fleet.drained", rep=rep,
                              host=r["host"], rc=None,
                              exit_kind="host-death",
                              was_ready=entry["was_ready"])
                continue
            # wanted capacity that died with its machine: replace on
            # a survivor, resize bucket (same as the ready case)
            self.valves.admit(now, resize=True)
            self.replaced_total += 1
            self.router.fleet_event("replace")
            record = {"action": "replace", "rep": rep,
                      "host": r["host"], "cause": "host-death",
                      "counted": False, "verdict": "respawn",
                      "ts": now}
            with self._lock:
                self.history.append(record)
            flight.record("fleet.replace", rep=rep, host=r["host"],
                          cause="host-death", counted=False)
            self._error("replica %d (%s) stranded on lost host %d — "
                        "reaped and replaced", rep, prev, r["host"])

    def _strike_lost_hosts(self, now):
        """The strike ladder at fleet scope: a host whose agent has
        been gone for ``loss_strikes`` windows is LOST — new
        placements avoid it until its agent re-registers (which
        restores it; replicas flow back via reconciliation when the
        autoscaler next needs the room)."""
        with self._lock:
            for h, s in self.hosts.items():
                if h in self.lost_hosts:
                    continue
                gone = (s["conn"] is None or not s["conn"].alive)
                if not gone or s["down_since"] is None:
                    continue
                if now - s["down_since"] >= \
                        self.loss_strikes * self.loss_window_s:
                    self.lost_hosts.add(h)
                    lost = sorted(self.lost_hosts)
                    flight.record("fleet.host_lost", host=h,
                                  strikes=self.loss_strikes,
                                  lost=lost)
                    self._error("host %d classified LOST (%d "
                                "windows silent) — placements avoid "
                                "it until its agent returns", h,
                                self.loss_strikes)

    # -------------------------------------------------------------- tick
    def _tick(self, now):
        with self._lock:
            phase = self.phase
        if phase in ("done", "giveup"):
            return
        if self.spawn_agents:
            self._respawn_dead_agents()
        if phase == "stopping":
            self._tick_stopping(now)
            return
        if phase == "gathering":
            # no placements until every agent registered (bounded by
            # a grace window): the first reconcile run against a
            # partial host set would pile the whole minimum onto
            # whichever agent connected first, concentrating exactly
            # the capacity a host kill is supposed to only dent
            with self._lock:
                all_up = all(s["conn"] is not None and s["conn"].alive
                             for s in self.hosts.values())
            grace = max(self.loss_strikes * self.loss_window_s, 10.0)
            if all_up or (self._started_ts is not None
                          and now - self._started_ts > grace):
                with self._lock:
                    self.phase = "running"
                self._info("placement opens on host(s) %s",
                           self.live_hosts() or "<none>")
            else:
                return
        self._strike_lost_hosts(now)
        self._handle_host_death_replicas(now)
        self._expire_stuck_spawns(now)
        if self.autoscale and \
                now - self._last_autoscale >= self.autoscale_interval_s:
            self._last_autoscale = now
            self._autoscale_step(now)
        self._reconcile(now)
        if now - self._last_note >= 1.0:
            self._last_note = now
            with self._lock:
                self.router.note_fleet(
                    desired=self.desired,
                    hosts=len(self.live_hosts()),
                    lost_hosts=sorted(self.lost_hosts),
                    scale_events=self.valves.scale_events,
                    scale_damped=self.valves.scale_damped,
                    replaced=self.replaced_total,
                    hold_replace=self.hold_replace)

    def _expire_stuck_spawns(self, now):
        """A replica that never announced READY within the budget is
        a wedged spawn: kill it (its exit report does the counted
        accounting) — it must not hold a fleet slot forever."""
        with self._lock:
            stuck = [(rep, r) for rep, r in self.reps.items()
                     if r["state"] == "spawning"
                     and now - r["spawn_ts"] > self.ready_timeout_s]
            for rep, r in stuck:
                r["state"] = "dying"
                self._send(r["host"], {"type": "kill_replica",
                                       "rep": rep})
        for rep, r in stuck:
            flight.record("fleet.ready_timeout", rep=rep,
                          host=r["host"])
            self._error("replica %d never announced READY in %.0fs — "
                        "killing the spawn", rep, self.ready_timeout_s)

    def _autoscale_step(self, now):
        signals = self.router.fleet_signals()
        with self._lock:
            desired = self.desired
        delta, reason = self.autoscaler.decide(
            now, desired, self.fleet_min, self.fleet_max, signals)
        if not delta:
            return
        verdict = self.valves.admit_scale(now)
        direction = "up" if delta > 0 else "down"
        if verdict == "damped":
            flight.record("fleet.scale_damped", direction=direction,
                          reason=reason)
            self._info("autoscale %s damped (flap valve): %s",
                       direction, reason)
            return
        with self._lock:
            self.desired = min(self.fleet_max,
                               max(self.fleet_min, desired + delta))
            new = self.desired
            self.history.append({"action": "scale",
                                 "direction": direction,
                                 "from": desired, "to": new,
                                 "reason": reason, "ts": now})
        self.router.fleet_event("scale", direction)
        flight.record("fleet.scale", direction=direction,
                      desired=new, was=desired, reason=reason,
                      signals=signals)
        self._info("autoscale %s: desired %d -> %d (%s)", direction,
                   desired, new, reason)

    def _reconcile(self, now):
        with self._lock:
            live = self.live_hosts()
            placements = {rep: r["host"]
                          for rep, r in self.reps.items()
                          if r["state"] in ("spawning", "ready",
                                            "dying")}
            draining = [rep for rep, r in self.reps.items()
                        if r["state"] == "draining"]
            drainable = [rep for rep, r in self.reps.items()
                         if r["state"] == "ready"]
            desired = self.desired
            if self.hold_replace is not None:
                # a valve verdict holds the fleet at what is live —
                # no replacement/growth spawns until an operator
                # intervenes (scale-down drains still allowed)
                desired = min(desired, len(placements))
        spawns, drains = plan_fleet(desired, live, self.per_host,
                                    placements, draining,
                                    drainable=drainable)
        for host in spawns:
            self._spawn_replica_on(host, now)
        for rep in drains:
            self._drain_rep(rep, now)

    def _want_role(self):
        """Role for the NEXT spawn (lock held): fill the prefill tier
        up to ``prefill_replicas``, then decode — so a dead prefill
        replica's replacement automatically inherits the deficit, and
        role balance is plain reconciliation, not a special case."""
        if self.prefill_replicas <= 0:
            return None
        live_prefill = sum(
            1 for r in self.reps.values()
            if r["state"] in ("spawning", "ready")
            and r.get("role") == "prefill")
        return ("prefill" if live_prefill < self.prefill_replicas
                else "decode")

    def _spawn_replica_on(self, host, now):
        with self._lock:
            rep = self._next_rep
            self._next_rep += 1
            argv = list(self.replica_argv) + \
                list(self.host_extras.get(host, ()))
            role = self._want_role()
            self.reps[rep] = {"host": host, "state": "spawning",
                              "rid": None, "port": None, "pid": None,
                              "spawn_ts": now, "ready_ts": None,
                              "exit": None, "role": role}
            env = {"VELES_TPU_REPLICA_ROLE": role} if role else {}
            sent = self._send(host, {"type": "spawn_replica",
                                     "rep": rep, "argv": argv,
                                     "env": env})
            if not sent:
                # the agent died between planning and send: the next
                # tick re-plans over the live hosts
                self.reps[rep]["state"] = "dead"
                self.reps[rep]["exit"] = {"rc": None,
                                          "kind": "agent-unreachable",
                                          "signature": None}
                return
        flight.record("fleet.spawn", rep=rep, host=host)
        self._info("spawning replica %d on host %d", rep, host)

    def _drain_rep(self, rep, now):
        with self._lock:
            rec = self.reps.get(rep)
            if rec is None or rec["state"] not in ("spawning",
                                                   "ready"):
                return
            rec["state"] = "draining"
            rid, host = rec["rid"], rec["host"]
        flight.record("fleet.drain", rep=rep, host=host, rid=rid)
        self._info("scale-down: draining replica %d on host %d", rep,
                   host)
        if rid is not None:
            # stop routing to it immediately; its in-flight requests
            # finish (the router marks it draining and POSTs /drain)
            self.router.drain_replica(rid)
        with self._lock:
            self._send(host, {"type": "drain_replica", "rep": rep})

    # ----------------------------------------------------------- shutdown
    def _begin_shutdown_drain(self):
        with self._lock:
            reps = [(rep, r) for rep, r in self.reps.items()
                    if r["state"] in ("spawning", "ready")]
        for rep, r in reps:
            self._drain_rep(rep, time.time())
        with self._lock:
            self._shutdown_deadline = time.time() + \
                max(self.kill_grace_s * 2, 10.0)

    def _tick_stopping(self, now):
        with self._lock:
            # a replica whose host's agent is gone can never report
            # its exit — waiting on it only burns the deadline
            live = [rep for rep, r in self.reps.items()
                    if r["state"] in ("spawning", "ready", "dying",
                                      "draining")
                    and self.hosts[r["host"]]["conn"] is not None
                    and self.hosts[r["host"]]["conn"].alive]
            done = not live or now >= self._shutdown_deadline
            if done:
                self.phase = "done" if self.rc in (None, 0) \
                    else "giveup"
                if self.rc is None:
                    self.rc = 0

    def _shutdown(self):
        with self._lock:
            for h in self.hosts:
                self._send(h, {"type": "shutdown",
                               "grace_ms":
                                   int(self.kill_grace_s * 1e3)})
        deadline = time.time() + self.kill_grace_s + 10
        for host, proc in self._agent_procs.items():
            while proc.poll() is None and time.time() < deadline:
                time.sleep(0.05)
            if proc.poll() is None:
                try:
                    proc.terminate()
                    proc.wait(timeout=5)
                except (OSError, subprocess.TimeoutExpired):
                    try:
                        proc.kill()
                    except OSError:
                        pass
        try:
            self._listener.close()
        except OSError:
            pass
        try:
            self.router.stop()
        except Exception:   # noqa: BLE001 — best-effort teardown
            pass

    def _info(self, msg, *args):
        self._log.info(msg, *args)
        print("[fleet] " + msg % args, file=sys.stderr, flush=True)

    def _error(self, msg, *args):
        self._log.error(msg, *args)
        print("[fleet] " + msg % args, file=sys.stderr, flush=True)


# =====================================================================
# the per-host agent
# =====================================================================

class PodAgent(object):
    """One host's supervisor agent: spawn/kill the local worker on the
    master's orders, classify its deaths (shared taxonomy with the
    single-host Supervisor), heartbeat liveness + step/commit progress,
    scan the host-local checkpoint directory for the agreement, and
    fence any zombie worker a previous agent life left behind."""

    def __init__(self, master_addr, host_id, workdir,
                 heartbeat_ms=None):
        self.master_addr = master_addr
        self.host = int(host_id)
        self.workdir = os.path.abspath(workdir)
        self.heartbeat_s = float(
            heartbeat_ms if heartbeat_ms is not None
            else root.common.pod.get("heartbeat_ms", 500)) / 1e3
        self.progress_file = os.path.join(self.workdir, "progress")
        self.pidfile = os.path.join(self.workdir, "worker.pid")
        self._conn = None
        self._child = None
        self._spec = None
        self._spawned_ts = None
        #: serving replicas this agent runs for a ServeFleetMaster:
        #: rep_id -> {"proc", "port", "spec", "log_path"}
        self._replicas = {}
        #: (snapshot_dir, prefix, scan) from the last report_manifests
        #: — the worker is dead for the whole agree->spawn round, so the
        #: rollback can reuse it instead of re-hashing the ring
        self._manifest_scan = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._log = logging.getLogger("PodAgent%d" % self.host)

    # -------------------------------------------------------------- main
    def run(self):
        os.makedirs(self.workdir, exist_ok=True)
        self._fence_orphan()
        host, _, port = self.master_addr.rpartition(":")
        sock = socket.create_connection((host, int(port)), timeout=30)
        # the connect timeout must NOT persist as a read timeout: the
        # master is silent for the whole of normal training (heartbeats
        # flow agent->master only), so a timed read would misread any
        # quiet 30s as a lost master and kill a healthy worker.  A real
        # master death closes the socket (EOF) and unblocks the read.
        # lint-ok: VW904 — EOF is the liveness signal on this socket
        sock.settimeout(None)
        self._conn = _Conn(sock)
        self._conn.send({"type": "register", "host": self.host,
                         "incarnation": None, "pid": os.getpid()})
        hello = self._conn.recv()
        if hello and hello.get("type") == "refused":
            # the master names why (duplicate host, register-first,
            # fenced incarnation) — surface it instead of the raw dict
            self._print("registration refused: %s",
                        hello.get("reason", "unspecified"))
            return 1
        if not hello or hello.get("type") != "welcome":
            self._print("registration refused: %s", hello)
            return 1
        if "heartbeat_ms" in hello:
            self.heartbeat_s = float(hello["heartbeat_ms"]) / 1e3
        hb = threading.Thread(target=self._heartbeat_loop,
                              name="AgentHeartbeat", daemon=True)
        hb.start()
        rc = 0
        while not self._stop.is_set():
            msg = self._conn.recv()
            if msg is None:
                # master gone: a headless worker would hang in its next
                # collective anyway once peers restart — fail closed
                self._print("master connection lost — killing worker")
                self._kill_worker(grace_s=2.0)
                rc = 1
                break
            t = msg.get("type")
            if t == "spawn":
                self._handle_spawn(msg)
            elif t == "kill_worker":
                grace = float(msg.get("grace_ms", 5000)) / 1e3
                threading.Thread(target=self._kill_worker,
                                 args=(grace,), name="AgentKiller",
                                 daemon=True).start()
            elif t == "report_manifests":
                self._report_manifests(msg)
            elif t == "fetch_commit":
                self._fetch_commit(msg)
            elif t == "push_commit":
                self._push_commit(msg)
            elif t == "spawn_replica":
                self._spawn_replica(msg)
            elif t == "drain_replica":
                # lossless scale-down: SIGTERM → the replica's
                # install_sigterm_drain stops admission, finishes
                # in-flight, exits 0 (reported as replica_exit done)
                self._signal_replica(msg.get("rep"), signal.SIGTERM,
                                     "drain")
            elif t == "kill_replica":
                self._signal_replica(msg.get("rep"), signal.SIGKILL,
                                     "kill")
            elif t == "fence":
                self._print("fenced by master (%s) — killing worker",
                            msg.get("reason"))
                flight.record("pod.fenced", host=self.host,
                              reason=msg.get("reason"))
                self._kill_worker(grace_s=0.0)
            elif t == "shutdown":
                grace = float(msg.get("grace_ms", 5000)) / 1e3
                self._shutdown_replicas(grace)
                self._kill_worker(grace_s=grace)
                break
        self._stop.set()
        self._conn.close()
        return rc

    # ------------------------------------------------------------- fence
    def _fence_orphan(self):
        """Kill any worker OR serving replica a previous agent life
        left running (their pids survive in pidfiles): a zombie from
        an old incarnation must never reach the new collective — and
        a zombie replica must never keep serving (or re-register)
        after the fleet already replaced it."""
        pidfiles = [self.pidfile]
        try:
            pidfiles += sorted(
                os.path.join(self.workdir, n)
                for n in os.listdir(self.workdir)
                if n.startswith("replica-") and n.endswith(".pid"))
        except OSError:
            pass
        for path in pidfiles:
            self._fence_pidfile(path)

    def _fence_pidfile(self, pidfile):
        try:
            fields = open(pidfile).read().split()
            pid = int(fields[0])
            ticks = int(fields[1]) if len(fields) > 1 else None
        except (OSError, ValueError, IndexError):
            return
        try:
            os.kill(pid, 0)
        except OSError:
            return
        # the pid alone is not an identity — after a host reboot (or
        # pid wraparound) it can belong to an innocent process.  Kill
        # only a process whose kernel start time matches the one
        # recorded at spawn; with no recorded ticks (no /proc), fall
        # back to requiring a veles_tpu worker cmdline.
        if ticks is not None:
            if _proc_start_ticks(pid) != ticks:
                self._print("stale pidfile pid %d was recycled — "
                            "not fencing", pid)
                try:
                    os.remove(pidfile)
                except OSError:
                    pass
                return
        else:
            try:
                with open("/proc/%d/cmdline" % pid, "rb") as f:
                    cmdline = f.read()
            except OSError:
                cmdline = None
            if cmdline is not None and b"veles_tpu" not in cmdline:
                self._print("stale pidfile pid %d is not a worker — "
                            "not fencing", pid)
                try:
                    os.remove(pidfile)
                except OSError:
                    pass
                return
        self._print("fencing orphan pid %d from a previous agent "
                    "life (%s)", pid, os.path.basename(pidfile))
        flight.record("pod.orphan_fenced", host=self.host, pid=pid,
                      pidfile=os.path.basename(pidfile))
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError:
            pass
        try:
            os.remove(pidfile)
        except OSError:
            pass

    # ------------------------------------------------------------- spawn
    def _handle_spawn(self, msg):
        with self._lock:
            if self._child is not None and self._child.poll() is None:
                # a live worker across a spawn order is itself a zombie
                # hazard — replace it
                self._print("spawn with live worker pid %d — killing "
                            "it first", self._child.pid)
                self._kill_child_locked(0.0)
        quarantined = []
        if msg.get("rollback"):
            from veles_tpu.services.snapshotter import rollback_to_commit
            scan, self._manifest_scan = self._manifest_scan, None
            if scan is not None and \
                    scan[:2] != (msg["snapshot_dir"], msg["prefix"]):
                scan = None
            quarantined = rollback_to_commit(
                msg["snapshot_dir"], msg["prefix"], msg.get("agreed"),
                quarantine=msg.get("quarantine"),
                scan=None if scan is None else scan[2])
            flight.record("pod.rollback", host=self.host,
                          agreed=msg.get("agreed"),
                          quarantined=quarantined)
            if quarantined:
                self._print("rolled back to %s (quarantined: %s)",
                            msg.get("agreed"), quarantined)
        env = merge_worker_env(os.environ, msg.get("env", {}))
        env["VELES_TPU_PROGRESS_FILE"] = self.progress_file
        env["PYTHONUNBUFFERED"] = "1"
        incarnation = msg.get("incarnation", 0)
        log_path = os.path.join(self.workdir,
                                "attempt-%03d.log" % incarnation)
        try:
            os.remove(self.progress_file)
        except OSError:
            pass
        log = open(log_path, "wb")
        try:
            child = subprocess.Popen(msg["argv"], env=env, stdout=log,
                                     stderr=log)
        except OSError as e:
            log.close()
            self._print("worker spawn failed: %s", e)
            self._send({"type": "worker_exit", "host": self.host,
                        "incarnation": incarnation, "rc": 127,
                        "kind": "crash:SpawnError", "signature": str(e)})
            return
        with self._lock:
            self._child = child
            self._spec = dict(msg, log_path=log_path)
            self._spawned_ts = time.time()
            try:
                ticks = _proc_start_ticks(child.pid)
                with open(self.pidfile, "w") as f:
                    f.write(str(child.pid) if ticks is None
                            else "%d %d" % (child.pid, ticks))
            except OSError:
                pass
        self._send({"type": "worker_up", "host": self.host,
                    "incarnation": incarnation, "pid": child.pid,
                    "quarantined": quarantined})
        threading.Thread(target=self._wait_worker,
                         args=(child, log, dict(self._spec)),
                         name="AgentWaiter", daemon=True).start()

    def _wait_worker(self, child, log, spec):
        rc = child.wait()
        log.close()
        spawned = self._spawned_ts or 0.0
        kind, signature = classify_exit(
            rc, spec.get("blackbox_dir"), spawned)
        if kind.startswith("killed:"):
            # the sandbox XLA/glibc abort (ROADMAP "Known environment
            # flake"): an abort-class signal with a startup-shaped log
            # (small, no traceback — a Python-level death always
            # leaves one; the memory-corruption class kills the
            # process from under the interpreter) is an environment
            # fault, not a training death — the master respawns it
            # uncounted.  A DETERMINISTIC abort is still bounded: with
            # the agreed checkpoint not advancing, the master's
            # flake-streak valve gives up (``env-flake-storm``).
            sig_name = kind.split(":", 1)[1]
            flaky = {signal.Signals(s).name
                     for s in STARTUP_FLAKE_SIGNALS}
            if sig_name in flaky and \
                    self._startup_shaped_log(spec.get("log_path")):
                kind = "env-flake"
        # drop the pidfile only if it still records THIS child: a spawn
        # order that replaced a live worker has already written the new
        # worker's pid, and deleting it here would blind _fence_orphan
        # to exactly the zombie the fence exists for
        with self._lock:
            try:
                mine = open(self.pidfile).read().split()[0] \
                    == str(child.pid)
            except (OSError, ValueError, IndexError):
                mine = False
            if mine:
                try:
                    os.remove(self.pidfile)
                except OSError:
                    pass
        self._send({"type": "worker_exit", "host": self.host,
                    "incarnation": spec.get("incarnation"),
                    "rc": rc, "kind": kind, "signature": signature})

    @staticmethod
    def _startup_shaped_log(log_path, limit=STARTUP_FLAKE_OUTPUT_LIMIT):
        """True when the attempt log looks like it never got past
        startup: small and free of a Python traceback.  A real
        training death prints more (epoch lines, flight markers, or a
        traceback) before dying."""
        if log_path is None:
            return False
        try:
            with open(log_path, "rb") as f:
                data = f.read(limit + 1)
        except OSError:
            return False
        return len(data) <= limit and b"Traceback" not in data

    # -------------------------------------------------------------- kill
    def _kill_worker(self, grace_s):
        with self._lock:
            self._kill_child_locked(grace_s)

    def _kill_child_locked(self, grace_s):
        child = self._child
        if child is None or child.poll() is not None:
            return
        try:
            child.send_signal(signal.SIGTERM)
        except OSError:
            return
        deadline = time.time() + grace_s
        while child.poll() is None and time.time() < deadline:
            time.sleep(0.05)
        if child.poll() is None:
            # a worker blocked inside a collective (or a forged stall)
            # never reaches its SIGTERM handler — escalate
            try:
                child.kill()
            except OSError:
                pass

    # ----------------------------------------------- serving replicas
    def _replica_pidfile(self, rep):
        return os.path.join(self.workdir, "replica-%03d.pid" % rep)

    def _spawn_replica(self, msg):
        """Spawn one serving replica on the master's order: start the
        replica command with stdout piped, tee it into
        ``replica-NNN.log`` while scanning for the READY handshake
        (``restful.READY_LINE``), report ``replica_up`` with the bound
        port, and report ``replica_exit`` — classified with the shared
        supervisor taxonomy — when it dies."""
        rep = int(msg["rep"])
        old = self._replicas.get(rep)
        if old is not None and old["proc"].poll() is None:
            # a live process under a reused id is a zombie hazard —
            # the master never reuses rep ids, so this is defensive
            self._print("spawn_replica %d with live process pid %d — "
                        "killing it first", rep, old["proc"].pid)
            try:
                old["proc"].kill()
            except OSError:
                pass
        env = merge_worker_env(os.environ, msg.get("env", {}))
        env["PYTHONUNBUFFERED"] = "1"
        # any `--serve` command announces READY under an agent
        env["VELES_TPU_REPLICA_ANNOUNCE"] = "1"
        # fleet membership, surfaced on the replica's own
        # web_status /api/health
        env["VELES_TPU_FLEET_HOST"] = str(self.host)
        env["VELES_TPU_FLEET_REP"] = str(rep)
        log_path = os.path.join(self.workdir,
                                "replica-%03d.log" % rep)
        try:
            log = open(log_path, "ab")
        except OSError as e:
            self._send({"type": "replica_exit", "host": self.host,
                        "rep": rep, "rc": 127,
                        "kind": "crash:SpawnError",
                        "signature": str(e)})
            return
        try:
            proc = subprocess.Popen(msg["argv"], env=env,
                                    stdout=subprocess.PIPE,
                                    stderr=log)
        except OSError as e:
            log.close()
            self._print("replica %d spawn failed: %s", rep, e)
            self._send({"type": "replica_exit", "host": self.host,
                        "rep": rep, "rc": 127,
                        "kind": "crash:SpawnError",
                        "signature": str(e)})
            return
        self._replicas[rep] = {"proc": proc, "port": None,
                               "spec": dict(msg),
                               "log_path": log_path}
        try:
            ticks = _proc_start_ticks(proc.pid)
            with open(self._replica_pidfile(rep), "w") as f:
                f.write(str(proc.pid) if ticks is None
                        else "%d %d" % (proc.pid, ticks))
        except OSError:
            pass
        flight.record("fleet.replica_spawn", host=self.host, rep=rep,
                      pid=proc.pid)
        threading.Thread(target=self._replica_pump,
                         args=(rep, proc, log),
                         name="AgentReplica%d" % rep,
                         daemon=True).start()

    def _replica_pump(self, rep, proc, log):
        """Read the replica's stdout line by line (teeing into its
        log — the pipe must keep draining or the replica blocks on a
        full buffer), announce ``replica_up`` at the READY line, and
        report the classified exit when the stream ends."""
        from veles_tpu.services.restful import parse_ready_line
        announced = False
        try:
            for raw in proc.stdout:
                try:
                    log.write(raw)
                    log.flush()
                except OSError:
                    pass
                if not announced:
                    ready = parse_ready_line(
                        raw.decode("utf-8", "replace"))
                    if ready is not None:
                        announced = True
                        self._replicas[rep]["port"] = ready["port"]
                        self._send({"type": "replica_up",
                                    "host": self.host, "rep": rep,
                                    "port": ready["port"],
                                    "pid": proc.pid})
        except (OSError, ValueError):
            pass
        rc = proc.wait()
        log.close()
        kind, signature = classify_exit(rc)
        if kind.startswith("killed:"):
            # same env-flake fingerprint as the training worker: an
            # abort-class death with a startup-shaped log is the
            # sandbox environment, not the replica binary — the
            # master replaces it uncounted
            sig_name = kind.split(":", 1)[1]
            flaky = {signal.Signals(s).name
                     for s in STARTUP_FLAKE_SIGNALS}
            if sig_name in flaky and not announced and \
                    self._startup_shaped_log(
                        self._replicas[rep]["log_path"]):
                kind = "env-flake"
        with self._lock:
            try:
                mine = open(self._replica_pidfile(rep)).read().split()
                mine = mine and mine[0] == str(proc.pid)
            except (OSError, ValueError, IndexError):
                mine = False
            if mine:
                try:
                    os.remove(self._replica_pidfile(rep))
                except OSError:
                    pass
        self._send({"type": "replica_exit", "host": self.host,
                    "rep": rep, "rc": rc, "kind": kind,
                    "signature": signature,
                    "announced": announced})

    def _signal_replica(self, rep, sig, what):
        rec = self._replicas.get(rep)
        if rec is None or rec["proc"].poll() is not None:
            return
        self._print("%s replica %d (pid %d)", what, rep,
                    rec["proc"].pid)
        try:
            rec["proc"].send_signal(sig)
        except OSError:
            pass

    def _shutdown_replicas(self, grace_s):
        """Agent shutdown: SIGTERM every replica (they drain and exit
        0), escalate to SIGKILL past the grace."""
        live = [rec["proc"] for rec in self._replicas.values()
                if rec["proc"].poll() is None]
        for proc in live:
            try:
                proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = time.time() + grace_s
        for proc in live:
            while proc.poll() is None and time.time() < deadline:
                time.sleep(0.05)
            if proc.poll() is None:
                try:
                    proc.kill()
                except OSError:
                    pass

    # --------------------------------------------------------- telemetry
    def _heartbeat_loop(self):
        while not self._stop.is_set():
            with self._lock:
                child, spec = self._child, self._spec
            alive = child is not None and child.poll() is None
            age = None
            if spec is not None:
                paths = [self.progress_file, spec.get("snapshot_dir")]
                newest = newest_mtime([p for p in paths if p])
                if newest is not None:
                    age = max(time.time() - newest, 0.0)
            msg = {"type": "heartbeat", "host": self.host,
                   "incarnation": None if spec is None
                   else spec.get("incarnation"),
                   "worker_alive": alive, "progress_age": age}
            if not self._send(msg):
                return
            self._stop.wait(self.heartbeat_s)

    def _report_manifests(self, msg):
        from veles_tpu.services.snapshotter import scan_commits
        commits = scan_commits(msg["snapshot_dir"], msg["prefix"])
        self._manifest_scan = (msg["snapshot_dir"], msg["prefix"],
                               commits)
        # mtimes/paths are host-local; ship JSON-clean entries
        self._send({"type": "manifests", "host": self.host,
                    "commits": commits})

    # -------------------------------------------- commit replication
    def _fetch_commit(self, msg):
        """Read one commit (data file + manifest sidecar) and ship it
        base64 over the control plane — the survivor's half of the
        re-expansion transfer (the returning host's ring is frozen at
        the loss point)."""
        import base64
        from veles_tpu.services.snapshotter import MANIFEST_SUFFIX
        name, directory = msg["name"], msg["snapshot_dir"]
        cap = float(msg.get("max_mb", 64)) * (1 << 20)
        files, err = {}, None
        for fname in (name, name + MANIFEST_SUFFIX):
            path = os.path.join(directory, fname)
            try:
                if os.path.getsize(path) > cap:
                    err = "%s exceeds the %.0f MiB replication cap " \
                        "(pod.replicate_max_mb; use shared storage " \
                        "for checkpoints this size)" \
                        % (fname, cap / (1 << 20))
                    break
                with open(path, "rb") as f:
                    files[fname] = base64.b64encode(
                        f.read()).decode("ascii")
            except OSError as e:
                err = "%s: %s" % (fname, e)
                break
        self._send({"type": "commit_data", "host": self.host,
                    "name": name, "ok": err is None,
                    "files": files if err is None else None,
                    "error": err})

    def _push_commit(self, msg):
        """Write a replicated commit into the local ring (tmp+rename,
        so a crash mid-transfer never leaves a half-written commit the
        next agreement could mistake for local state) — the returning
        host's half of the transfer."""
        import base64
        directory, err = msg["snapshot_dir"], None
        try:
            os.makedirs(directory, exist_ok=True)
            for fname, b64 in (msg.get("files") or {}).items():
                fname = os.path.basename(fname)   # no path traversal
                path = os.path.join(directory, fname)
                tmp = path + ".tmp"   # scans skip ``.tmp`` leftovers
                with open(tmp, "wb") as f:
                    f.write(base64.b64decode(b64))
                os.replace(tmp, path)
            # the cached agreement scan predates the transfer
            self._manifest_scan = None
        except (OSError, ValueError) as e:
            err = str(e)
        flight.record("pod.commit_pushed", host=self.host,
                      ok=err is None, error=err)
        self._send({"type": "commit_pushed", "host": self.host,
                    "ok": err is None, "error": err})

    def _send(self, obj):
        return self._conn is not None and self._conn.send(obj)

    def _print(self, msg, *args):
        self._log.info(msg, *args)
        print("[agent%d] %s" % (self.host, msg % args),
              file=sys.stderr, flush=True)


# =====================================================================
# CLI
# =====================================================================

def main(argv=None):
    p = argparse.ArgumentParser(
        prog="veles-tpu-pod",
        description="multi-host pod master / per-host supervisor agent "
        "(docs/distributed_training.md \"Pod orchestration\").  Master: "
        "veles-tpu-pod --hosts 2 --prefix wf -- python -m veles_tpu "
        "wf.py --snapshot auto ...  Agent (one per host; spawned "
        "automatically unless --no-agents): veles-tpu-pod --agent "
        "--master HOST:PORT --host-id I --workdir DIR")
    p.add_argument("--agent", action="store_true",
                   help="run as a per-host agent instead of the master")
    p.add_argument("--master", default=None, metavar="HOST:PORT",
                   help="(agent) the master's control address")
    p.add_argument("--host-id", type=int, default=None,
                   help="(agent) this host's index")
    p.add_argument("--workdir", default=None,
                   help="state directory (agent logs/pidfile/progress; "
                   "master layout root)")
    p.add_argument("--hosts", type=int, default=2,
                   help="(master) number of hosts in the pod")
    p.add_argument("--port", type=int, default=0,
                   help="(master) control-plane TCP port (0 = pick)")
    p.add_argument("--bind-host", default="127.0.0.1")
    p.add_argument("--coordinator-host", default="127.0.0.1",
                   help="(master) host 0's address for "
                   "jax.distributed coordinators (a fresh port per "
                   "incarnation)")
    p.add_argument("--prefix", required=False, default="wf",
                   help="(master) the workflow's snapshot prefix — "
                   "what the checkpoint agreement scans for")
    p.add_argument("--snapshot-root", default=None,
                   help="(master) per-host snapshot dirs live at "
                   "SNAPSHOT_ROOT/host<i>")
    p.add_argument("--devices-per-host", type=int, default=None,
                   help="(master) force K virtual CPU devices per "
                   "worker (XLA_FLAGS; local pod emulation)")
    p.add_argument("--no-agents", action="store_true",
                   help="(master) do not spawn local agents — print "
                   "each host's agent command instead (real pods run "
                   "one agent per machine)")
    p.add_argument("--json", default=None, metavar="FILE",
                   help="(master) write the final status/history here")
    p.add_argument("--serve", action="store_true",
                   help="run the SERVING fleet master instead of the "
                   "training pod master: the command after `--` is "
                   "the replica command (it must print the "
                   "REPLICA_READY handshake — any `python -m "
                   "veles_tpu ... --serve 0` does under an agent); "
                   "the fleet spec comes from root.common.serve."
                   "fleet.{min,max,per_host} unless overridden "
                   "(docs/services.md 'Autoscaling fleet')")
    p.add_argument("--fleet-min", type=int, default=None,
                   help="(--serve) minimum replicas fleet-wide")
    p.add_argument("--fleet-max", type=int, default=None,
                   help="(--serve) maximum replicas fleet-wide")
    p.add_argument("--per-host", type=int, default=None,
                   help="(--serve) max replicas on any one host")
    p.add_argument("--prefill-replicas", type=int, default=None,
                   help="(--serve) run this many replicas as the "
                   "PREFILL tier: long prompts' admission prefill "
                   "routes there, the decode continues on a decode "
                   "replica via the prefix-resume splice "
                   "(docs/services.md 'Disaggregated prefill')")
    p.add_argument("--router-port", type=int, default=0,
                   help="(--serve) the fleet router's HTTP port "
                   "(0 = pick)")
    p.add_argument("--health-interval-ms", type=float, default=None,
                   help="(--serve) the router's health-probe period")
    p.add_argument("--no-autoscale", action="store_true",
                   help="(--serve) hold the fleet at --fleet-min "
                   "instead of following the measured load")
    p.add_argument("worker", nargs=argparse.REMAINDER,
                   help="(master) the worker command, after `--` "
                   "(the replica command with --serve)")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    if args.agent:
        if args.master is None or args.host_id is None \
                or args.workdir is None:
            p.error("--agent needs --master, --host-id and --workdir")
        agent = PodAgent(args.master, args.host_id, args.workdir)
        return agent.run()

    worker = list(args.worker)
    if worker and worker[0] == "--":
        worker = worker[1:]
    if not worker:
        p.error("master mode needs the %s command after `--`"
                % ("replica" if args.serve else "worker"))
    if args.serve:
        master = ServeFleetMaster(
            worker, n_hosts=args.hosts, workdir=args.workdir,
            port=args.port, bind_host=args.bind_host,
            fleet_min=args.fleet_min, fleet_max=args.fleet_max,
            per_host=args.per_host, router_port=args.router_port,
            health_interval_ms=args.health_interval_ms,
            autoscale=not args.no_autoscale,
            prefill_replicas=args.prefill_replicas,
            spawn_agents=not args.no_agents)
        try:
            rc = master.run()
        except KeyboardInterrupt:
            master.stop()
            rc = master.wait(60)
        report = master.status()
        report["history"] = master.history
        if args.json:
            with open(args.json, "w") as f:
                json.dump(report, f, indent=2, default=str)
        print(json.dumps({k: report[k] for k in
                          ("phase", "desired", "live_replicas",
                           "replaced_total", "scale_events",
                           "lost_hosts")}, default=str))
        return rc if rc is not None else 1
    master = PodMaster(
        worker, n_hosts=args.hosts, snapshot_root=args.snapshot_root,
        prefix=args.prefix, workdir=args.workdir, port=args.port,
        bind_host=args.bind_host, coordinator_host=args.coordinator_host,
        devices_per_host=args.devices_per_host,
        spawn_agents=not args.no_agents)
    rc = master.run()
    report = master.status()
    report["history"] = master.history
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2, default=str)
    print(json.dumps({k: report[k] for k in
                      ("phase", "incarnation", "restarts",
                       "restart_causes", "degraded", "lost_hosts",
                       "resize_restarts", "rc")}, default=str))
    return rc if rc is not None else 1


if __name__ == "__main__":
    sys.exit(main())
