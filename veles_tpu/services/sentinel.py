"""Numeric-fault sentinel — the survival tier for *numerical* death
(docs/distributed_training.md "Numeric-fault survival").

PRs 8-11 made process death, host death, torn commits and collective
hangs survivable, every recovery gated bit-exact — but a NaN'd run kept
burning TPU steps while the ``reject_nonfinite`` commit valve silently
refused every checkpoint: detection at commit time, recovery never.
This module closes that gap with a three-rung response ladder over
cheap **in-jit health probes** fused into the staged train step
(:meth:`~veles_tpu.models.nn_units.StagedTrainer` calls
:func:`apply_probes` inside its jitted step; the results ride a
device-resident health accumulator read back at the existing
``read_class_stats`` sync point — **no extra device sync per step**):

* **probes** — loss finiteness, gradient global-norm finiteness, an
  EWMA loss-spike z-score (armed only after ``spike_warmup``
  observations), and update-norm explosion.  All f32 scalar math, all
  guarded (``maximum`` + eps before every division) so the VN4xx
  numerics audit stays clean on the step that carries them.
* **rung 1: in-jit skip-update** — a poisoned step's update is zeroed
  via ``where`` select (params/velocity keep their pre-step values,
  bit-deterministically), counted, and its step number recorded; the
  run never dispatches host work mid-step.
* **rung 2: rollback-and-replay** — after ``strikes_to_rollback``
  anomalous sweeps the sentinel rolls the run back to the last
  **healthy** commit (commits carry a health stamp in their manifest,
  surfaced by ``scan_commits`` without unpickling), quarantines the
  newer/unhealthy ring tail (the shared ``rollback_to_commit``), and
  replays with the poisoned global minibatch on the trainer's traced
  **skip list** — the Loader serves global indices, so the replayed
  trajectory is bit-identical to a run that skipped that batch from
  the start (the ``tools/numerics_chaos.py`` gate, threshold 0).
* **rung 3: escalation** — ``rollbacks_to_escalate`` rollback (or
  containment) rounds with an identical anomaly signature raise
  :class:`NumericFaultError`: the crashdump carries a
  ``sentinel.giveup`` event, ``classify_exit`` turns it into a
  ``numerics:<kind>`` crash class, and the Supervisor / PodMaster
  deterministic-bug valves bound it with a diagnosis instead of
  crash-looping.

Where rung 2 is impossible — a multi-host pod (pod-scope rollback
rides the existing coordinated restart, whose cross-host checkpoint
agreement prefers healthy-stamped commits), rollback disabled, no
snapshotter, or no healthy commit yet — the incident is **contained**:
rung 1 already kept the live state clean, so training continues and
only persistence (the same-signature counter) escalates.

Rollback and replay are **progress**, not a hang: every rung-2 step
calls ``telemetry.health.note_progress()`` so the hang watchdog and the
pod master's collective-hang latch can never mistake a rollback window
for a wedged pod.  Config: ``root.common.sentinel.*``."""

import numpy as np

from veles_tpu.config import root
from veles_tpu.telemetry import flight
from veles_tpu.units import Unit

#: anomaly kinds, in diagnosis priority order — when a sweep carries
#: several, the signature is the highest-priority one (a nonfinite
#: gradient usually CAUSES the downstream loss spike)
ANOMALY_KINDS = ("nonfinite_grad", "nonfinite_loss", "update_explosion",
                 "loss_spike")

#: health-accumulator counter keys, one per anomaly kind plus the
#: aggregate/skip bookkeeping — every leaf is an f32 scalar so the
#: device tree stays uniform (replicated under a mesh like the class
#: stats)
_COUNTER_KEYS = ANOMALY_KINDS + ("anomalies", "skipped", "policy_skips")


class NumericFaultError(RuntimeError):
    """Rung 3: persistent numerical divergence the rollback ladder
    could not outrun.  The message IS the diagnosis; the paired
    ``sentinel.giveup`` flight event gives the crash its
    ``numerics:<kind>`` class and stable signature so restart loops
    stop instead of faithfully replaying divergence forever."""

    def __init__(self, kind, diagnosis):
        super(NumericFaultError, self).__init__(diagnosis)
        self.kind = kind


def probe_config():
    """The sentinel's build-time knobs as a plain dict (static floats —
    they are baked into the jitted step, never traced)."""
    ns = root.common.get("sentinel")
    cfg = ns.as_dict() if hasattr(ns, "as_dict") else dict(ns or {})
    out = {
        "enabled": bool(cfg.get("enabled", True)),
        "spike_zscore": float(cfg.get("spike_zscore", 12.0)),
        "spike_warmup": float(cfg.get("spike_warmup", 64)),
        "update_norm_limit": cfg.get("update_norm_limit", 1e6),
        "ewma_decay": float(cfg.get("ewma_decay", 0.99)),
        "max_skip_steps": max(1, int(cfg.get("max_skip_steps", 8))),
        "force_skip_steps": tuple(
            int(s) for s in (cfg.get("force_skip_steps") or ())),
    }
    return out


#: "no poisoned step recorded yet" sentinel value for the int32 step
#: marks (int32 so a step counter past 2^24 — where f32 loses integer
#: exactness — still arms the replay skip list with the RIGHT step)
NO_BAD_STEP = np.int32(np.iinfo(np.int32).max)


def init_health():
    """Fresh device-resident health accumulator (f32 scalars, plus
    int32 step marks).  NOT checkpointed: health state only influences
    params through skip decisions, and keeping it out of the snapshot
    is what lets the rollback-replay final state compare bit-identical
    to a golden skip-batch run (whose sentinel never struck)."""
    import jax.numpy as jnp
    tree = {"ewma_mean": jnp.zeros((), jnp.float32),
            "ewma_var": jnp.zeros((), jnp.float32),
            "obs": jnp.zeros((), jnp.float32),
            "first_bad_step": jnp.full((), NO_BAD_STEP, jnp.int32),
            "last_bad_step": jnp.full((), -1, jnp.int32)}
    for k in _COUNTER_KEYS:
        tree[k] = jnp.zeros((), jnp.float32)
    return tree


def skip_steps_array(steps, capacity):
    """The trainer's traced skip list: int32 ``[capacity]`` padded with
    -1 (no real step counter is ever -1 — ``_run_step`` increments
    before dispatch).  Values change between dispatches without a
    recompile; the CAPACITY is the static shape."""
    arr = np.full((int(capacity),), -1, np.int32)
    steps = sorted(set(int(s) for s in steps))[: int(capacity)]
    arr[: len(steps)] = steps
    return arr


def _tree_sumsq_f32(tree):
    import jax
    import jax.numpy as jnp
    total = jnp.zeros((), jnp.float32)
    for leaf in jax.tree_util.tree_leaves(tree):
        if jnp.issubdtype(jnp.result_type(leaf), jnp.floating):
            total = total + jnp.sum(
                jnp.square(leaf.astype(jnp.float32)))
    return total


def apply_probes(health, loss, grads, new_params, params, step,
                 skip_steps, cfg):
    """The in-jit probe + rung-1 select gate.  Traced inside the staged
    train step; returns ``(health', ok)`` where ``ok`` (scalar bool)
    decides whether this step's update applies — the caller selects
    ``where(ok, new, old)`` per leaf, which is bit-exact in both
    directions.

    Probes (all f32, all anomaly flags sticky into the counters):

    * ``nonfinite_loss`` — the optimized mean loss is NaN/inf;
    * ``nonfinite_grad`` — the gradient tree's global sum of squares is
      NaN/inf (NaN propagates through the reduction; an overflowed-to-
      inf but elementwise-finite gradient lands here too — it is just
      as fatal to the update);
    * ``update_explosion`` — the applied update's global L2 norm
      exceeds ``update_norm_limit`` (finite-but-divergent steps);
    * ``loss_spike`` — EWMA z-score of the loss above ``spike_zscore``,
      armed only after ``spike_warmup`` observations (cold statistics
      must not fire on normal early-training descent).

    The EWMA advances only on finite, non-anomalous, non-skipped steps,
    so one NaN cannot poison the baseline it is judged against.  A
    **policy skip** (``step`` present in ``skip_steps`` — the replay
    list, or the golden run's ``force_skip_steps``) gates the update
    identically but is NEVER counted as an anomaly, whatever its
    numerics: the step was already adjudicated, its update cannot
    apply, and re-striking on it would turn one step-keyed fault into
    an endless rollback loop.  Golden-skip and rollback-replay
    trajectories therefore stay bit-identical: both take exactly this
    code path with the same update gate and the same EWMA gate."""
    import jax.numpy as jnp

    f32 = jnp.float32
    loss_f = jnp.asarray(loss, f32)
    step_i = jnp.asarray(step, jnp.int32)
    finite_loss = jnp.isfinite(loss_f)
    grad_ss = _tree_sumsq_f32(grads)
    finite_grad = jnp.isfinite(grad_ss)
    upd_ss = _tree_sumsq_f32(
        jax_tree_sub(new_params, params))
    limit = cfg.get("update_norm_limit")
    if limit:
        # compare squared norms: no sqrt, and a NaN upd_ss compares
        # False (it is already caught by nonfinite_grad)
        exploded = upd_ss > f32(float(limit)) ** 2
    else:
        exploded = jnp.zeros((), bool)
    mean, var, obs = health["ewma_mean"], health["ewma_var"], health["obs"]
    warm = obs >= f32(cfg["spike_warmup"])
    # guarded std: maximum with a positive literal keeps the divisor
    # provably positive (VN400-clean)
    std = jnp.sqrt(jnp.maximum(var, f32(1e-12)))
    z = (loss_f - mean) / std
    spiked = warm & finite_loss & (z > f32(cfg["spike_zscore"]))
    raw_bad = (~finite_loss) | (~finite_grad) | exploded | spiked
    policy = jnp.any(step == skip_steps)
    ok = ~(raw_bad | policy)
    not_pol = ~policy
    bad = raw_bad & not_pol

    d = f32(cfg["ewma_decay"])
    track = finite_loss & ~raw_bad & not_pol
    delta = jnp.where(finite_loss, loss_f - mean, f32(0.0))
    health = dict(health)
    health["ewma_mean"] = jnp.where(track, mean + (1.0 - d) * delta,
                                    mean)
    health["ewma_var"] = jnp.where(
        track, d * var + (1.0 - d) * jnp.square(delta), var)
    health["obs"] = obs + jnp.where(track, f32(1.0), f32(0.0))
    flags = {"nonfinite_loss": ~finite_loss & not_pol,
             "nonfinite_grad": ~finite_grad & not_pol,
             "update_explosion": exploded & not_pol,
             "loss_spike": spiked & not_pol,
             "anomalies": bad, "skipped": bad, "policy_skips": policy}
    for k, flag in flags.items():
        health[k] = health[k] + jnp.where(flag, f32(1.0), f32(0.0))
    health["first_bad_step"] = jnp.where(
        bad, jnp.minimum(health["first_bad_step"], step_i),
        health["first_bad_step"])
    health["last_bad_step"] = jnp.where(bad, step_i,
                                        health["last_bad_step"])
    return health, ok


def jax_tree_sub(a, b):
    """Leafwise ``a - b`` in f32 (the update tree for the explosion
    probe) — non-float leaves pass through as zeros-shaped floats so
    the sumsq above simply ignores them."""
    import jax
    import jax.numpy as jnp

    def sub(x, y):
        if jnp.issubdtype(jnp.result_type(x), jnp.floating):
            return x.astype(jnp.float32) - y.astype(jnp.float32)
        return jnp.zeros((), jnp.float32)

    return jax.tree_util.tree_map(sub, a, b)


def dominant_kind(deltas):
    """The sweep's anomaly signature: the highest-priority kind with a
    nonzero delta (:data:`ANOMALY_KINDS` order)."""
    for kind in ANOMALY_KINDS:
        if deltas.get(kind, 0) > 0:
            return kind
    return None


class HealthSentinel(Unit):
    """The host-side half of the ladder: strike accounting at the sync
    point, rollback-and-replay, escalation.  Linked at the workflow
    tail (after the snapshotter) so the poisoned sweep's commit — stamped
    unhealthy — exists before the rollback decision quarantines it.

    Demands: ``trainer``, ``loader``; ``snapshotter`` is optional (no
    commits to roll back to degrades rung 2 to escalation)."""

    def __init__(self, workflow, **kwargs):
        super(HealthSentinel, self).__init__(workflow, **kwargs)

        def knob(key, default):
            if key in kwargs:
                return kwargs[key]
            return root.common.sentinel.get(key, default)

        self.strikes_to_rollback = max(1, int(
            knob("strikes_to_rollback", 1)))
        self.rollbacks_to_escalate = max(1, int(
            knob("rollbacks_to_escalate", 3)))
        self.rollback_enabled = bool(knob("rollback", True))
        self.demand("trainer", "loader")
        self.snapshotter = None
        self.view_group = "SERVICE"
        self.strikes = 0
        self.rollbacks = 0
        #: consecutive rollbacks with the same anomaly signature — the
        #: escalation counter (rung 3)
        self.same_signature_rollbacks = 0
        self.last_signature = None
        #: cumulative device-counter values at the last observed sweep
        self._seen = {k: 0.0 for k in _COUNTER_KEYS}
        #: the unhealthy sweep waiting for run() to act on, or None
        self._pending = None
        self.history = []

    # ------------------------------------------------------ observation
    def observe_sweep(self, cls, stats, health_host):
        """Called by the trainer at the ``read_class_stats`` sync point
        with the freshly fetched health scalars.  Pure bookkeeping —
        computes counter deltas since the last sweep and latches an
        unhealthy sweep for run() to act on at the next cycle boundary
        (rolling back MID-cycle would yank state out from under the
        decision unit)."""
        deltas = {}
        for k in _COUNTER_KEYS:
            cur = float(health_host.get(k, 0.0))
            deltas[k] = cur - self._seen.get(k, 0.0)
            self._seen[k] = cur
        if deltas.get("anomalies", 0) <= 0:
            return None
        kind = dominant_kind(deltas) or "unknown"
        first_bad = int(health_host.get("first_bad_step", NO_BAD_STEP))
        pending = {
            "anomaly": kind,
            "class": int(cls),
            "deltas": {k: int(v) for k, v in deltas.items() if v},
            "first_bad_step": None if first_bad == NO_BAD_STEP
            else first_bad,
            "last_bad_step": int(health_host.get("last_bad_step", -1)),
        }
        self._pending = pending
        reset = getattr(self.trainer, "reset_health_marks", None)
        if callable(reset):
            reset()
        self._telemetry("sentinel.anomaly", pending)
        return pending

    def _telemetry(self, event, payload):
        """Anomaly observability — fail-soft per the telemetry rules
        (the LADDER itself never rides this path)."""
        try:
            from veles_tpu import telemetry
            flight.record(event, **payload)
            telemetry.registry.counter(
                "veles_sentinel_anomalies_total",
                "anomalous staged steps detected by the in-jit health "
                "probes", ("kind",)).inc(
                payload["deltas"].get(payload["anomaly"], 1) or 1,
                kind=payload["anomaly"])
            self.warning(
                "numeric anomaly in sweep: %s (deltas %s, first bad "
                "step %s)", payload["anomaly"], payload["deltas"],
                payload["first_bad_step"])
        except Exception:   # noqa: BLE001 — observe, never abort
            pass

    # ------------------------------------------------------- the ladder
    def run(self):
        pending, self._pending = self._pending, None
        if pending is None:
            return
        self.strikes += 1
        if self.strikes < self.strikes_to_rollback:
            return
        self.strikes = 0
        sig = pending["anomaly"]
        if sig == self.last_signature:
            self.same_signature_rollbacks += 1
        else:
            self.last_signature = sig
            self.same_signature_rollbacks = 1
        if self.same_signature_rollbacks > self.rollbacks_to_escalate:
            self._escalate(
                pending, "persistent %s after %d rollback/containment "
                "rounds" % (sig, self.same_signature_rollbacks - 1))
        import jax
        pod = jax.process_count() > 1
        if pod or not self.rollback_enabled or self.snapshotter is None:
            # rung 1 already contained the poisoned updates in-jit, so
            # a run that CANNOT roll back locally — a pod (every host
            # computes this identical decision from replicated health
            # values; recovery for a persistent fault rides the
            # coordinated restart, whose agreement prefers healthy
            # commits), rollback disabled, or simply no snapshotter —
            # keeps training on its still-clean state.  Persistence
            # escalates through the same-signature counter above.
            self._contain(
                pending,
                "pod-scope (recovery rides the coordinated restart)"
                if pod else "in-process rollback disabled "
                "(root.common.sentinel.rollback=False)"
                if not self.rollback_enabled else
                "no snapshotter configured")
            return
        self._rollback(pending)

    def _drain_commit_verdict(self):
        """Consume the trainer's commit-verdict delta so the incident
        just adjudicated cannot leak into the NEXT commit's health
        stamp.  Matters when the anomalous epoch itself did not commit
        (snapshot interval > 1, wall-clock gating): without this the
        first clean post-rollback/containment commit would compute a
        nonzero anomaly delta and be stamped unhealthy — and then be
        skipped by every later rollback and ranked down by the pod
        agreement, despite holding perfectly clean state."""
        verdict = getattr(self.trainer, "health_verdict", None)
        if callable(verdict):
            verdict()

    def _contain(self, pending, why):
        """Rung 1 was the whole response: count the adjudicated
        incident (it still feeds the escalation counter) and let the
        run continue on its protected state."""
        self._drain_commit_verdict()
        record = {"anomaly": pending["anomaly"], "reason": why,
                  "round": self.same_signature_rollbacks,
                  "first_bad_step": pending["first_bad_step"]}
        self.history.append(dict(record, contained=True))
        flight.record("sentinel.contained", **record)
        self.warning(
            "numeric anomaly contained in-jit (%s): %s — round %d/%d "
            "before escalation", pending["anomaly"], why,
            self.same_signature_rollbacks,
            self.rollbacks_to_escalate + 1)

    def _escalate(self, pending, why):
        diagnosis = (
            "numeric fault (%s): %s; first bad step %s, anomaly "
            "deltas %s — giving up so the restart ladder classifies "
            "this as numerics:%s instead of crash-looping"
            % (pending["anomaly"], why, pending["first_bad_step"],
               pending["deltas"], pending["anomaly"]))
        flight.record("sentinel.giveup", anomaly=pending["anomaly"],
                      signature=pending["anomaly"],
                      first_bad_step=pending["first_bad_step"],
                      rollbacks=self.rollbacks, diagnosis=diagnosis)
        self.error("sentinel giving up: %s", diagnosis)
        raise NumericFaultError(pending["anomaly"], diagnosis)

    def _rollback(self, pending):
        """Rung 2: restore the last healthy commit and arm the replay
        skip list with the poisoned step.  Every stage notes progress —
        a rollback window must read as the run WORKING to the hang
        watchdog and the pod master's collective-hang latch."""
        from veles_tpu.services.snapshotter import (
            SnapshotterBase, rollback_to_commit, scan_commits)
        from veles_tpu.telemetry import health as health_mod
        health_mod.note_progress()
        snap = self.snapshotter
        scan = scan_commits(snap.directory, snap.prefix)
        target = self._newest_healthy(scan)
        if target is None:
            # nothing committed yet (or everything stamped unhealthy):
            # rung 1 kept the live state clean, so containment beats
            # both an impossible rollback and a premature death
            self._contain(pending, "no healthy commit in %s"
                          % snap.directory)
            return
        self.rollbacks += 1
        quarantined = rollback_to_commit(snap.directory, snap.prefix,
                                         target, scan=scan)
        state = SnapshotterBase.import_(scan[target]["path"])
        health_mod.note_progress()
        self.workflow.restore(state)
        dec = getattr(self.workflow, "decision", None)
        if dec is not None:
            # a rollback in the FINAL epoch would otherwise leave the
            # stop condition latched from the poisoned timeline and end
            # the run before the replay; the decision recomputes it at
            # every epoch boundary from the restored counters
            dec.complete <<= False
        bad_step = pending["first_bad_step"]
        if bad_step is not None:
            self.trainer.add_skip_steps([bad_step])
        self._drain_commit_verdict()
        health_mod.note_progress()
        record = {"commit": target,
                  "epoch": scan[target].get("epoch"),
                  "anomaly": pending["anomaly"], "skip_step": bad_step,
                  "quarantined": quarantined,
                  "rollback": self.rollbacks}
        self.history.append(record)
        flight.record("sentinel.rollback", **record)
        try:
            from veles_tpu import telemetry
            telemetry.registry.counter(
                "veles_sentinel_rollbacks_total",
                "automatic rollbacks to the last healthy commit",
                ("kind",)).inc(kind=pending["anomaly"])
        except Exception:   # noqa: BLE001
            pass
        # the loud, parseable marker the numerics-chaos gate counts
        self.info(
            "sentinel rollback #%d: %s at step %s -> restored healthy "
            "commit %s (epoch %s), replaying with the poisoned "
            "minibatch skipped (quarantined: %s)",
            self.rollbacks, pending["anomaly"], bad_step, target,
            scan[target].get("epoch"), quarantined)

    @staticmethod
    def _newest_healthy(scan):
        """The newest commit that is valid AND not stamped unhealthy —
        legacy commits without a health stamp count as healthy (same
        benefit-of-the-doubt the agreement gives them)."""
        from veles_tpu.services.snapshotter import _commit_order_key
        best_key, best = None, None
        for name, entry in scan.items():
            if entry.get("valid") is not True:
                continue
            if str(entry.get("health") or "").startswith("unhealthy"):
                continue
            key = _commit_order_key(name, [entry])
            if best_key is None or key > best_key:
                best_key, best = key, name
        return best

    def get_metric_values(self):
        return {"sentinel": {
            "rollbacks": self.rollbacks,
            "strikes": self.strikes,
            "last_signature": self.last_signature,
            "anomalies_seen": int(self._seen.get("anomalies", 0)),
            "policy_skips_seen": int(self._seen.get("policy_skips", 0)),
        }}
