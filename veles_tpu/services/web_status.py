"""Web-status dashboard (ref: veles/web_status.py:113-314 + the node.js
frontend in web/).

The reference ran a Tornado server fed by POSTs from masters, with MongoDB
log browsing.  Here a stdlib HTTP server serves: ``/`` (HTML dashboard),
``/api/status`` (registered workflow metrics), ``/api/events`` (the
structured trace ring buffer from veles_tpu.logger), ``/api/plots`` (the
PlotBus payloads), and accepts POST ``/update`` from remote runs — same
capability surface, no external deps."""

import json
import math
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from veles_tpu import telemetry
from veles_tpu.logger import Logger, events
from veles_tpu.services.plotting import bus

_PAGE = """<!DOCTYPE html>
<html><head><title>veles_tpu status</title>
<style>body{font-family:monospace;margin:2em}table{border-collapse:collapse}
td,th{border:1px solid #999;padding:4px 8px}
.spark{display:inline-block;margin:0 1.5em .8em 0}
.spark svg{vertical-align:middle;background:#f6f6f6}
.spark .v{color:#06c}
#graph svg,#timeline svg{background:#fafafa;border:1px solid #ddd}
.node{font-size:11px}.lane{font-size:10px;fill:#555}</style></head>
<body><h2>veles_tpu status <span id="health"></span></h2>
<div id="status"></div><h3>metrics</h3><div id="metrics"></div>
<h3>telemetry <small>(process metrics registry —
<a href="/metrics">prometheus</a> ·
<a href="/api/telemetry">json</a>)</small></h3>
<div id="mfu"></div><div id="telemetry"></div>
<h3>serving <small>(ContinuousEngine slot pool: queue depth,
p50/p99 queue-wait and per-stream decode rate)</small></h3>
<div id="serving">(no serving endpoint registered)</div>
<h3>workflow graph <small>(nodes heat-colored by run-time share;
<a href="/api/dot">DOT</a>)</small></h3><div id="graph"></div>
<h3>event timeline <small>(<a href="/api/trace">chrome trace</a> —
load in Perfetto / chrome://tracing)</small></h3>
<div id="timeline"></div>
<h3>device profiler <small>(jax.profiler window over the live process;
<a href="/api/profile/trace">latest trace</a> — load in
Perfetto)</small></h3>
<div><button onclick="capProf()">capture 3s</button>
<span id="prof"></span></div>
<script>
async function capProf(){
 const r=await (await fetch('/api/profile',{method:'POST',
  body:JSON.stringify({seconds:3})})).json();
 document.getElementById('prof').textContent=JSON.stringify(r);
 setTimeout(async()=>{
  const s=await (await fetch('/api/profile')).json();
  document.getElementById('prof').textContent=JSON.stringify(s);},4000);
}
</script>
<h3>bench <small>(last on-chip capture vs the roofline model's
prediction — <a href="/api/bench">json</a>)</small></h3>
<div id="bench"></div>
<script>
(async function(){
 try{
  const b=await (await fetch('/api/bench')).json();
  const m=b.measured||{}, p=b.predicted||{};
  const keys=['value','gemm_bf16_gflops','lm_large_tokens_per_sec',
   'lm_large_mfu','lm_tokens_per_sec','alexnet_samples_per_sec',
   'flash_ms_long_t8192','serve_ms_per_tok_int8','mlp_step_fused_ms',
   'beam_ms_per_pos_t4096'];
  let h='<table border=0 cellpadding=3><tr><th align=left>metric'+
   '</th><th>measured</th><th>predicted</th><th>ratio</th></tr>';
  for(const k of keys){
   const mv=m[k], pv=p[k];
   if(mv==null&&pv==null)continue;
   const r=(mv&&pv)?(mv/pv).toFixed(2):'';
   h+='<tr><td>'+k+'</td><td align=right>'+(mv??'')+
    '</td><td align=right>'+(pv??'')+'</td><td align=right>'+r+
    '</td></tr>';
  }
  h+='</table><small>measured_at '+(b.measured_at||'never')+
   '</small>';
  document.getElementById('bench').innerHTML=h;
 }catch(e){document.getElementById('bench').textContent=String(e);}
})();
</script>
<h3>perf ledger <small>(persistent per-key history + regression
sentinel — <a href="/api/perf">json</a>; drift rides
<a href="/metrics">/metrics</a> as veles_perf_drift /
veles_perf_regressions_total)</small></h3>
<div id="perf"></div>
<script>
(async function(){
 try{
  const p=await (await fetch('/api/perf')).json();
  const ks=p.keys||[];
  if(!ks.length){document.getElementById('perf').textContent=
   '(empty ledger: '+(p.ledger||p.error||'?')+')';return;}
  let h='<table><tr><th align=left>key</th><th>trend</th>'+
   '<th>last</th><th>median</th><th>drift</th><th>target</th>'+
   '<th>verdict</th></tr>';
  for(const k of ks.slice(0,40)){
   const v=k.verdict||{};
   const pts=(k.trend||[]).map((y,i)=>[i,y]);
   const badge=v.status==='regression'?
    '<b style="color:#c00">regression</b>':
    v.status==='improved'?'<b style="color:#2a2">improved</b>':
    esc(v.status||'?');
   h+='<tr><td>'+esc(k.key)+'</td><td>'+
    (pts.length>1?sparkline(pts):'')+'</td><td align=right>'+
    esc(k.last??'')+'</td><td align=right>'+
    (v.median==null?'':Number(v.median).toPrecision(4))+
    '</td><td align=right>'+
    (v.drift==null?'':(100*v.drift).toFixed(1)+'%')+
    '</td><td align=right>'+esc(v.target??'')+'</td><td>'+badge+
    (v.target_met===false?
     ' <b style="color:#c60">target missed</b>':'')+'</td></tr>';
  }
  document.getElementById('perf').innerHTML=h+'</table>';
 }catch(e){document.getElementById('perf').textContent=String(e);}
})();
</script>
<h3>recent events</h3><div id="events"></div>
<h3>log browser <small>(cross-run, needs --log-db)</small></h3>
<div><input id="logq" placeholder="substring" size="24">
<select id="logrun"><option value="">all runs</option></select>
<button onclick="searchLogs()">search</button></div>
<div id="logs"></div>
<script>
async function loadRuns(){
 try{
  const r=await (await fetch('/api/logruns')).json();
  const sel=document.getElementById('logrun');
  (r.runs||[]).forEach(x=>{const o=document.createElement('option');
   o.value=x.session; o.textContent=x.session+' ('+x.records+')';
   sel.appendChild(o);});
 }catch(e){}
}
function esc(s){return String(s).replace(/&/g,'&amp;')
 .replace(/</g,'&lt;').replace(/>/g,'&gt;');}
async function searchLogs(){
 const q=encodeURIComponent(document.getElementById('logq').value);
 const s=encodeURIComponent(document.getElementById('logrun').value);
 const r=await (await fetch('/api/logs?q='+q+'&session='+s)).json();
 // esc(): log messages are data, never markup — a logged string
 // containing tags must render inert, not execute (stored-XSS guard)
 document.getElementById('logs').innerHTML = r.error ?
  '<i>'+esc(r.error)+'</i>' :
  '<pre>'+(r.logs||[]).map(x=>esc(new Date(x.ts*1000).toISOString()+' '+
   x.session+' '+x.level[0]+' '+x.logger+': '+x.message)).join('\\n')+
  '</pre>';
}
loadRuns();
</script>
<script>
function sparkSpan(k,pts){  // shared spark markup (metrics + serving)
 return '<span class="spark">'+esc(k)+' '+sparkline(pts)+
  ' <span class="v">'+pts[pts.length-1][1].toPrecision(4)+
  '</span></span>';
}
function sparkline(points){           // [[epoch, value], ...] -> SVG
 const w=120, h=28, vals=points.map(p=>p[1]);
 const lo=Math.min(...vals), hi=Math.max(...vals), span=(hi-lo)||1;
 const xs=points.map((p,i)=>[
  i*(w-2)/Math.max(points.length-1,1)+1,
  h-2-(p[1]-lo)*(h-4)/span]);
 return '<svg width="'+w+'" height="'+h+'"><polyline fill="none" '+
  'stroke="#06c" stroke-width="1.5" points="'+
  xs.map(q=>q[0].toFixed(1)+','+q[1].toFixed(1)).join(' ')+'"/></svg>';
}
function layers(g){   // longest-path-ish layering; repeater back-edges
 const n=g.nodes.length, adj=Array.from({length:n},()=>[]);   // ignored
 const indeg=new Array(n).fill(0);
 g.edges.forEach(([a,b])=>{adj[a].push(b); indeg[b]++;});
 const layer=new Array(n).fill(-1);
 let frontier=[]; indeg.forEach((d,i)=>{if(d===0)frontier.push(i);});
 if(!frontier.length && n)frontier=[0];
 frontier.forEach(i=>layer[i]=0);
 for(let depth=1; frontier.length && depth<n+1; depth++){
  const next=[];
  frontier.forEach(i=>adj[i].forEach(j=>{
   if(layer[j]<0){layer[j]=depth; next.push(j);}}));
  frontier=next;
 }
 layer.forEach((l,i)=>{if(l<0)layer[i]=0;});
 return layer;
}
function drawGraph(g){
 if(!g.nodes.length)return '(no units)';
 const layer=layers(g), cols={};
 g.nodes.forEach((nd,i)=>{(cols[layer[i]]=cols[layer[i]]||[]).push(i);});
 const cw=170, rh=48, bw=130, bh=30, pos={};
 Object.entries(cols).forEach(([l,ids])=>ids.forEach((id,r)=>{
  pos[id]=[l*cw+10, r*rh+12];}));
 const W=(Math.max(...Object.keys(cols).map(Number))+1)*cw+20;
 const H=Math.max(...Object.values(cols).map(c=>c.length))*rh+24;
 let s='<svg width="'+W+'" height="'+H+'">';
 s+='<defs><marker id="arr" markerWidth="7" markerHeight="7" refX="6" '+
  'refY="2.5" orient="auto"><path d="M0,0 L6,2.5 L0,5 z" fill="#888"/>'+
  '</marker></defs>';
 g.edges.forEach(([a,b])=>{
  const p=pos[a], q=pos[b], back=q[0]<=p[0];
  const x1=p[0]+(back?0:bw), y1=p[1]+bh/2, x2=q[0]+(back?bw:0),
   y2=q[1]+bh/2, bend=back?36:0;
  s+='<path d="M'+x1+','+y1+' C'+(x1+(back?-bend:40))+','+(y1+bend)+' '+
   (x2+(back?bend:-40))+','+(y2+bend)+' '+x2+','+y2+
   '" fill="none" stroke="'+(back?'#c60':'#888')+
   '" stroke-dasharray="'+(back?'4 3':'none')+'" marker-end="url(#arr)"/>';
 });
 g.nodes.forEach((nd,i)=>{
  const [x,y]=pos[i], heat=Math.min(nd.share*1.6,1);
  s+='<g class="node"><rect x="'+x+'" y="'+y+'" width="'+bw+'" height="'+
   bh+'" rx="5" fill="rgba(255,140,0,'+heat.toFixed(3)+
   ')" stroke="#555"><title>'+nd.cls+': '+nd.runs+' runs, '+
   nd.time+'s ('+(nd.share*100).toFixed(1)+'%)</title></rect>'+
   '<text x="'+(x+6)+'" y="'+(y+13)+'">'+nd.name.slice(0,19)+'</text>'+
   '<text x="'+(x+6)+'" y="'+(y+25)+'" fill="#666">'+nd.runs+'x '+
   nd.time.toFixed(2)+'s</text></g>';
 });
 return s+'</svg>';
}
function drawTimeline(evs){
 const spans=[], open={}, ticks=[];
 evs.forEach(e=>{
  const key=e.cat+':'+e.name;
  if(e.type==='begin')open[key]=e.time;
  else if(e.type==='end' && open[key]!==undefined){
   spans.push([key, open[key], e.time]); delete open[key];
  }else if(e.type==='single')ticks.push([key, e.time]);
 });
 const all=spans.map(s=>s[1]).concat(spans.map(s=>s[2]),
                                     ticks.map(t=>t[1]));
 if(!all.length)return '(no events yet)';
 const t0=Math.min(...all), t1=Math.max(...all), span=(t1-t0)||1;
 const lanes=[...new Set(spans.concat(ticks).map(s=>s[0]))].slice(0,12);
 const W=760, lh=20, X=t=>170+(t-t0)*(W-180)/span;
 let s='<svg width="'+W+'" height="'+(lanes.length*lh+24)+'">';
 lanes.forEach((ln,r)=>{
  const y=r*lh+14;
  s+='<text class="lane" x="2" y="'+(y+9)+'">'+ln.slice(0,26)+'</text>';
  spans.filter(sp=>sp[0]===ln).forEach(sp=>{
   s+='<rect x="'+X(sp[1])+'" y="'+y+'" width="'+
    Math.max(X(sp[2])-X(sp[1]),1.5)+'" height="12" fill="#06c" '+
    'opacity="0.65"><title>'+ln+' '+((sp[2]-sp[1])*1000).toFixed(1)+
    'ms</title></rect>';});
  ticks.filter(t=>t[0]===ln).forEach(t=>{
   s+='<circle cx="'+X(t[1])+'" cy="'+(y+6)+'" r="2.5" fill="#c60"/>';});
 });
 return s+'</svg>';
}
async function refresh(){
 try{   // health badge: green = alive, red = watchdog tripped (503)
  const hr=await fetch('/api/health'); const h=await hr.json();
  const bad=hr.status===503, wd=h.watchdog||{};
  document.getElementById('health').innerHTML=
   '<span style="font-size:13px;padding:2px 8px;border-radius:4px;'+
   'color:#fff;background:'+(bad?'#c00':'#2a2')+'">'+
   (bad?'WATCHDOG TRIPPED':'healthy')+'</span> <small>p'+
   esc(h.process_index)+' '+esc(h.mode||'?')+
   (h.last_progress_age_s!=null?
    ' · last step '+h.last_progress_age_s.toFixed(0)+'s ago':'')+
   (wd.armed?' · watchdog '+wd.window_s+'s':'')+
   (h.crashdumps?' · <b>'+h.crashdumps+' crashdump(s)</b>':'')+
   ' (<a href="/api/health">json</a>)</small>';
 }catch(e){}
 const s=await (await fetch('/api/status')).json();
 document.getElementById('status').innerHTML =
  '<pre>'+JSON.stringify(s,null,2)+'</pre>';
 if(s.serving){
  const c=s.serving.continuous||s.serving;
  const rows=Object.entries(c)
   .filter(([k,v])=>typeof v!=='object')
   .map(([k,v])=>'<tr><td>'+esc(k)+'</td><td>'+esc(v)+'</td></tr>')
   .join('');
  // client-side ring buffer -> live time-series of the SLO gauges
  // (one sample per refresh; the server only ever sends a snapshot)
  window._srv=window._srv||{};
  for(const k of ['agg_tokens_per_sec','queued','in_flight',
                  'p99_queue_wait_ms']){
   if(typeof c[k]==='number'){
    (window._srv[k]=window._srv[k]||[]).push([0,c[k]]);
    if(window._srv[k].length>120)window._srv[k].shift();
   }
  }
  const sparks=Object.entries(window._srv)
   .filter(([k,pts])=>pts.length>1)
   .map(([k,pts])=>sparkSpan(k,pts)).join('');
  document.getElementById('serving').innerHTML=
   sparks+'<table>'+rows+'</table>';
 }
 const m=await (await fetch('/api/metrics')).json();
 document.getElementById('metrics').innerHTML =
  Object.entries(m).map(([k,pts])=>sparkSpan(k,pts)).join('')
  || '(no epoch metrics yet)';
 const tl=await (await fetch('/api/telemetry')).json();
 const mfu=(tl.records||[]).filter(r=>r.kind==='mfu').pop();
 document.getElementById('mfu').innerHTML = mfu ?
  '<b>MFU</b> predicted '+mfu.predicted.toPrecision(3)+
  ' measured '+mfu.measured.toPrecision(3)+
  ' ratio '+mfu.ratio.toPrecision(3)+
  (mfu.warned?' <b style="color:#c00">SHORTFALL</b>':' ok')+
  ' <small>('+esc(mfu.device)+' roofline)</small>' : '';
 const trows=(tl.metrics||[]).filter(s=>s.kind!=='histogram')
  .slice(0,60)
  .map(s=>'<tr><td>'+esc(s.name)+'</td><td>'+
   esc(Object.entries(s.labels).map(([k,v])=>k+'='+v).join(','))+
   '</td><td align=right>'+
   (typeof s.value==='number'?s.value.toPrecision(5):esc(s.value))+
   '</td></tr>').join('');
 document.getElementById('telemetry').innerHTML = trows ?
  '<table><tr><th align=left>metric</th><th>labels</th>'+
  '<th>value</th></tr>'+trows+'</table>' : '(no samples yet)';
 const g=await (await fetch('/api/graph')).json();
 document.getElementById('graph').innerHTML =
  Object.entries(g).map(([name,wf])=>
   '<b>'+name+'</b><br>'+drawGraph(wf)).join('<br>') || '(no workflows)';
 const e=await (await fetch('/api/events')).json();
 document.getElementById('timeline').innerHTML = drawTimeline(e);
 document.getElementById('events').innerHTML =
  '<pre>'+e.slice(-30).map(x=>JSON.stringify(x)).join('\\n')+'</pre>';
}
refresh(); setInterval(refresh, 3000);
</script></body></html>"""


class WebStatusServer(Logger):
    def __init__(self, host=None, port=None):
        super(WebStatusServer, self).__init__()
        # explicit args win; the root.common.web knobs are the defaults
        # (--web-status PORT passes the port explicitly)
        from veles_tpu.config import root
        if host is None:
            host = str(root.common.web.get("host", "127.0.0.1"))
        if port is None:
            port = int(root.common.web.get("port", 8090))
        self.host, self.port = host, port
        self._workflows = {}
        self._serving = None
        self._updates = []
        self._server = None
        self._thread = None
        self._profile = {}
        self._lock = threading.Lock()

    def register(self, workflow):
        """Track a local workflow; its gather_results() feeds /api/status."""
        with self._lock:
            self._workflows[workflow.name] = workflow

    def register_serving(self, api):
        """Track a serving endpoint (RESTfulAPI or anything with
        ``serving_metrics()``/``metrics()``): its latency/throughput
        snapshot joins ``/api/status`` under ``"serving"`` and feeds
        the dashboard's serving panel."""
        with self._lock:
            self._serving = api

    def metrics(self, limit=200):
        """Per-epoch metric time series from the event ring: every
        numeric field of an ``epoch`` event becomes
        {series: [[epoch, value], ...]} — the dashboard's sparklines
        (ref the node.js status app's live charts, web/)."""
        skip = {"name", "cat", "type", "time", "epoch"}
        series = {}
        for ev in events.snapshot():
            if ev.get("name") != "epoch":
                continue
            ep = ev.get("epoch", 0)
            for k, v in ev.items():
                # non-finite values would serialize as the literal NaN,
                # which strict browser-side JSON.parse rejects
                if (k not in skip and isinstance(v, (int, float))
                        and math.isfinite(v)):
                    series.setdefault(k, []).append([ep, v])
        return {k: v[-limit:] for k, v in series.items()}

    def graph(self):
        """Control-graph JSON per registered workflow: nodes carry class,
        run count/time and run-time share (the dashboard heat-colors
        them), edges are the control links — the live equivalent of the
        reference's workflow SVG shipped in status POSTs
        (launcher.py:852-885)."""
        out = {}
        with self._lock:
            for name, wf in self._workflows.items():
                units = wf.units
                ids = {u: i for i, u in enumerate(units)}
                total = sum(u.run_time for u in units) or 1.0
                out[name] = {
                    "nodes": [{"id": i, "name": u.name,
                               "cls": type(u).__name__,
                               "runs": u.run_count,
                               "time": round(u.run_time, 4),
                               "share": round(u.run_time / total, 4)}
                              for u, i in ids.items()],
                    "edges": [[ids[u], ids[d]] for u in units
                              for d in u.links_to if d in ids],
                }
        return out

    def dot(self):
        """Concatenated DOT text of every registered workflow."""
        with self._lock:
            return "\n".join(wf.generate_graph()
                             for wf in self._workflows.values())

    @staticmethod
    def chrome_trace():
        """The event ring as a Chrome trace (chrome://tracing /
        Perfetto `trace.json`): begin/end pairs → B/E duration events,
        singles → instant events, lanes keyed by event category — the
        reference's Mongo event timeline as a standard tooling format."""
        out = []
        for ev in events.snapshot():
            ph = {"begin": "B", "end": "E", "single": "i"}.get(
                ev.get("type"))
            if ph is None:
                continue
            rec = {"name": ev.get("name", "?"), "ph": ph,
                   "ts": float(ev.get("time", 0.0)) * 1e6,   # µs
                   "pid": 0, "tid": ev.get("cat", "events")}
            if ph == "i":
                rec["s"] = "t"
            # finite numbers only — a NaN arg would serialize as the
            # bare literal NaN, which strict parsers (Perfetto,
            # JSON.parse) reject wholesale (same guard as metrics())
            extra = {k: v for k, v in ev.items()
                     if k not in ("name", "cat", "type", "time")
                     and isinstance(v, (int, float, str, bool))
                     and (not isinstance(v, float) or math.isfinite(v))}
            if extra:
                rec["args"] = extra
            out.append(rec)
        return {"traceEvents": out, "displayTimeUnit": "ms"}

    def profile_capture(self, seconds=3.0, outdir=None):
        """On-demand ``jax.profiler`` window over the LIVE process —
        the step timeline of where device time actually goes (TPU ops,
        HBM transfers, host dispatch), captured from the dashboard
        without restarting with ``--profile``.  The capture runs on a
        background thread; whatever the training loop executes during
        the window lands in the trace."""
        from veles_tpu.config import root
        with self._lock:
            if self._profile.get("running"):
                return {"error": "capture already running",
                        "dir": self._profile.get("dir")}
            d = outdir or os.path.join(
                root.common.dirs.get("profiles", "profiles"),
                time.strftime("web_%Y%m%d_%H%M%S"))
            self._profile = {"running": True, "dir": d,
                             "seconds": float(seconds)}

        def capture():
            import jax
            try:
                jax.profiler.start_trace(d)
                try:
                    time.sleep(float(seconds))
                finally:
                    # the profiler is a process-global singleton: an
                    # exception mid-window (interrupted sleep, writer
                    # error) must still stop the trace, or every later
                    # capture fails with "profiler already running"
                    jax.profiler.stop_trace()
                state = {"running": False, "dir": d,
                         "done_at": time.time()}
            except Exception as e:   # noqa: BLE001 — surface via GET
                state = {"running": False, "dir": d, "error": str(e)}
            with self._lock:
                self._profile = state

        threading.Thread(target=capture, daemon=True).start()
        return {"ok": True, "dir": d, "seconds": float(seconds)}

    def profile_trace(self):
        """The latest capture's chrome-trace JSON bytes (the profiler's
        ``*.trace.json.gz``, decompressed — loadable in Perfetto), or
        None when nothing has been captured."""
        import glob
        import gzip
        with self._lock:
            d = self._profile.get("dir")
            if not d or self._profile.get("running"):
                return None
        paths = sorted(glob.glob(os.path.join(
            d, "plugins", "profile", "*", "*.trace.json.gz")))
        if not paths:
            return None
        with gzip.open(paths[-1], "rb") as f:
            return f.read()

    def _log_db(self):
        from veles_tpu.config import root
        return root.common.web.get("log_db", None)

    def log_runs(self):
        """Cross-run session index from the sqlite log store (the
        reference's historical log browser, ref web_status.py:113-200 +
        the Mongo duplication it reads, logger.py:292-331)."""
        db = self._log_db()
        if not db or not os.path.exists(db):
            return {"error": "no log db (run with --log-db PATH)",
                    "runs": []}
        from veles_tpu.logger import log_sessions
        return {"runs": log_sessions(db)}

    def log_search(self, session=None, q=None, level=None, limit=200):
        """Search records across every run in the log store."""
        db = self._log_db()
        if not db or not os.path.exists(db):
            return {"error": "no log db (run with --log-db PATH)",
                    "logs": []}
        from veles_tpu.logger import search_logs
        return {"logs": search_logs(db, session=session, q=q,
                                    level=level, limit=limit)}

    def bench_report(self):
        """Predicted-vs-measured perf panel data: the bench's
        last-known-good cache (fetch-synced on-chip numbers, per-key
        dated) next to the offline roofline model's predictions — the
        dashboard view of the measurement-confirms-model loop
        (tools/cost_model.py; ref: the autotune DB as the reference's
        measurement store, veles/backends.py:672-731)."""
        from veles_tpu.config import root
        path = root.common.web.get("bench_cache", None)
        if not path:
            # default: the repo-root cache next to bench.py
            path = os.path.join(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
                ".bench_last_good.json")
        measured = {}
        if os.path.exists(path):
            try:
                with open(path) as f:
                    measured = json.load(f)
            except (OSError, ValueError):
                measured = {}
        predicted = {}
        try:
            from tools.cost_model import predictions_for_bench
            predicted = predictions_for_bench()
        except Exception:   # noqa: BLE001 — model optional at runtime
            predicted = {}
        return {"measured": measured, "predicted": predicted,
                "measured_at": measured.get("measured_at"),
                "cache_path": path}

    def perf_report(self):
        """``/api/perf`` payload: the persistent performance ledger
        (telemetry.ledger) grouped per key — trend values, latest
        sample, declared target, and the regression sentinel's verdict
        on that latest sample.  The sentinel's live gauges
        (``veles_perf_drift{metric}``,
        ``veles_perf_regressions_total``) ride the normal ``/metrics``
        Prometheus surface; this endpoint is the history view behind
        them.  Never raises — a perf panel that 500s hides the
        regression it exists to show."""
        try:
            from veles_tpu.telemetry import ledger
            book = ledger.default()
            keys = []
            for key, recs in sorted(book.by_key().items()):
                latest, prior = recs[-1], recs[:-1]
                verdict = book.assess(latest, prior)
                trend = [r.get("value") for r in recs[-32:]
                         if isinstance(r.get("value"), (int, float))]
                keys.append({"key": key,
                             "metric": latest.get("metric"),
                             "unit": latest.get("unit", ""),
                             "n": len(recs),
                             "last": latest.get("value"),
                             "ts": latest.get("ts"),
                             "trend": trend,
                             "verdict": verdict})
            return {"ledger": book.path, "keys": keys}
        except Exception as e:   # noqa: BLE001 — the panel must answer
            return {"error": str(e), "keys": []}

    def health_status(self):
        """``/api/health`` payload: process id/mode, last-step age,
        watchdog state, crashdump count (telemetry.health.status), plus
        — when a serving endpoint is registered — the lifecycle block
        (shed valve state, cancel/deadline/fault counters) under
        ``"serving"``, so an operator's probe sees load shedding the
        moment it starts.  Never raises — a health probe that 500s is
        worse than no probe."""
        try:
            from veles_tpu.telemetry import health
            state = health.status()
        except Exception as e:   # noqa: BLE001
            state = {"error": str(e), "watchdog": {"tripped": False}}
        with self._lock:
            serving = self._serving
        engine = getattr(serving, "engine", None)
        if engine is not None:
            try:
                state["serving"] = engine.lifecycle_status()
            except Exception as e:   # noqa: BLE001
                state["serving"] = {"error": str(e)}
        try:
            # pod-size block (threaded into workers by the pod master,
            # services.podmaster): probing ANY worker answers "how big
            # is the pod right now, and who is missing"
            from veles_tpu.config import root as _root
            pod = _root.common.get("pod")
            pod = pod.as_dict() if hasattr(pod, "as_dict") else None
            if pod and "size" in pod:
                state["pod"] = {
                    "size": pod.get("size"), "total": pod.get("total"),
                    "degraded": bool(pod.get("degraded")),
                    "lost_hosts": pod.get("lost_hosts") or []}
        except Exception:   # noqa: BLE001 — the probe must answer
            pass
        try:
            # fleet-membership block (env threaded in by the pod
            # agent, services.podmaster ServeFleetMaster): probing a
            # replica's dashboard answers "which fleet slot is this"
            host = os.environ.get("VELES_TPU_FLEET_HOST")
            rep = os.environ.get("VELES_TPU_FLEET_REP")
            role = os.environ.get("VELES_TPU_REPLICA_ROLE")
            if host is not None or rep is not None:
                state["fleet"] = {
                    "host": None if host is None else int(host),
                    "replica": None if rep is None else int(rep),
                    "role": role}
        except Exception:   # noqa: BLE001 — the probe must answer
            pass
        return state

    def status(self):
        out = {"time": time.time(), "workflows": {}, "remote": self._updates[-20:]}
        with self._lock:
            for name, wf in self._workflows.items():
                try:
                    out["workflows"][name] = wf.gather_results()
                except Exception as e:  # noqa: BLE001
                    out["workflows"][name] = {"error": str(e)}
            serving = self._serving
        if serving is not None:
            try:
                out["serving"] = (serving.serving_metrics()
                                  if hasattr(serving, "serving_metrics")
                                  else serving.metrics())
            except Exception as e:  # noqa: BLE001
                out["serving"] = {"error": str(e)}
        return out

    def start(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code, body, ctype="application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/":
                    self._send(200, _PAGE.encode(), "text/html")
                elif self.path == "/api/status":
                    self._send(200, json.dumps(server.status(),
                                               default=str).encode())
                elif self.path == "/api/events":
                    self._send(200, json.dumps(events.snapshot()[-200:],
                                               default=str).encode())
                elif self.path == "/api/metrics":
                    self._send(200, json.dumps(server.metrics(),
                                               default=str).encode())
                elif self.path == "/api/graph":
                    self._send(200, json.dumps(server.graph(),
                                               default=str).encode())
                elif self.path == "/api/dot":
                    self._send(200, server.dot().encode(), "text/plain")
                elif self.path == "/api/trace":
                    self._send(200, json.dumps(
                        server.chrome_trace()).encode())
                elif self.path.startswith("/api/trace/"):
                    # per-request span store (telemetry.tracing):
                    # this process's leg of a serving request's
                    # cross-process timeline, keyed by trace id
                    from veles_tpu.telemetry import tracing
                    tid = self.path[len("/api/trace/"):]
                    spans = tracing.store.spans(tid)
                    self._send(
                        200 if spans else 404,
                        json.dumps(
                            {"trace": tid, "spans": spans,
                             "phases": tracing.phases_of(spans)}
                        ).encode())
                elif self.path == "/api/plots":
                    self._send(200, json.dumps(bus.snapshot()[-20:],
                                               default=str).encode())
                elif self.path == "/api/profile":
                    with server._lock:
                        state = dict(server._profile)
                    self._send(200, json.dumps(state,
                                               default=str).encode())
                elif self.path == "/api/profile/trace":
                    body = server.profile_trace()
                    if body is None:
                        self._send(404, b'{"error": "no capture yet"}')
                    else:
                        self._send(200, body)
                elif self.path == "/metrics":
                    # Prometheus scrape surface (text format 0.0.4)
                    self._send(200,
                               telemetry.registry.render_prometheus()
                               .encode(),
                               "text/plain; version=0.0.4; "
                               "charset=utf-8")
                elif self.path == "/api/telemetry":
                    self._send(200, json.dumps(
                        {"metrics": telemetry.registry.snapshot(),
                         "records": telemetry.registry.records()[-60:]},
                        default=str).encode())
                elif self.path == "/api/health":
                    # liveness/forensics surface (telemetry.health):
                    # 503 once the hang watchdog has tripped, so a
                    # k8s-style probe (or a human's curl) distinguishes
                    # "serving but stalled" from healthy
                    state = server.health_status()
                    self._send(
                        503 if state.get("watchdog", {}).get("tripped")
                        else 200,
                        json.dumps(state, default=str).encode())
                elif self.path == "/api/bench":
                    self._send(200, json.dumps(server.bench_report(),
                                               default=str).encode())
                elif self.path == "/api/perf":
                    self._send(200, json.dumps(server.perf_report(),
                                               default=str).encode())
                elif self.path.startswith("/api/logruns"):
                    self._send(200, json.dumps(
                        server.log_runs(), default=str).encode())
                elif self.path.startswith("/api/logs"):
                    from urllib.parse import parse_qs, urlsplit
                    qs = {k: v[0] for k, v in parse_qs(
                        urlsplit(self.path).query).items()}
                    try:
                        limit = min(int(qs.get("limit", 200)), 10000)
                    except ValueError:
                        limit = 200
                    self._send(200, json.dumps(server.log_search(
                        session=qs.get("session"), q=qs.get("q"),
                        level=qs.get("level"), limit=limit),
                        default=str).encode())
                elif self.path == "/frontend":
                    # the command-composer page, generated live from the
                    # CLI arg registry (ref --frontend, launcher.py:199-267)
                    from veles_tpu.scripts import generate_frontend as gf
                    page = gf.render(gf.describe_parser(gf._main_parser()))
                    self._send(200, page.encode(), "text/html")
                else:
                    self.send_error(404)

            def do_POST(self):
                if self.path == "/api/profile":
                    length = int(self.headers.get("Content-Length", 0))
                    try:
                        req = json.loads(self.rfile.read(length) or b"{}")
                    except ValueError:
                        req = {}
                    if not isinstance(req, dict):
                        req = {}
                    try:
                        seconds = float(req.get("seconds", 3.0))
                    except (TypeError, ValueError):
                        self._send(400, b'{"error": "bad seconds"}')
                        return
                    # bound the window: the capture slot is singular and
                    # profiler overhead rides the live training loop
                    out = server.profile_capture(
                        seconds=min(max(seconds, 0.1), 60.0))
                    self._send(200, json.dumps(out).encode())
                    return
                # remote master update (ref web_status '/update' POST)
                if self.path != "/update":
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    update = json.loads(self.rfile.read(length))
                except ValueError:
                    self._send(400, b'{"error": "bad json"}')
                    return
                with server._lock:
                    server._updates.append(
                        {"time": time.time(), "update": update})
                self._send(200, b'{"ok": true}')

            def log_message(self, fmt, *args):
                server.debug("http: " + fmt, *args)

        # /metrics is now scrapeable: turn on the costly collections
        # (device-memory census) that are otherwise skipped
        telemetry.enable_collection()
        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        self.info("web status on http://%s:%d/", self.host, self.port)

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server = None
