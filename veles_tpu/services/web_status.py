"""Web-status dashboard (ref: veles/web_status.py:113-314 + the node.js
frontend in web/).

The reference ran a Tornado server fed by POSTs from masters, with MongoDB
log browsing.  Here a stdlib HTTP server serves: ``/`` (HTML dashboard),
``/api/status`` (registered workflow metrics), ``/api/events`` (the
structured trace ring buffer from veles_tpu.logger), ``/api/plots`` (the
PlotBus payloads), and accepts POST ``/update`` from remote runs — same
capability surface, no external deps."""

import json
import math
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from veles_tpu.logger import Logger, events
from veles_tpu.services.plotting import bus

_PAGE = """<!DOCTYPE html>
<html><head><title>veles_tpu status</title>
<style>body{font-family:monospace;margin:2em}table{border-collapse:collapse}
td,th{border:1px solid #999;padding:4px 8px}
.spark{display:inline-block;margin:0 1.5em .8em 0}
.spark svg{vertical-align:middle;background:#f6f6f6}
.spark .v{color:#06c}</style></head>
<body><h2>veles_tpu status</h2>
<div id="status"></div><h3>metrics</h3><div id="metrics"></div>
<h3>recent events</h3><div id="events"></div>
<script>
function sparkline(points){           // [[epoch, value], ...] -> SVG
 const w=120, h=28, vals=points.map(p=>p[1]);
 const lo=Math.min(...vals), hi=Math.max(...vals), span=(hi-lo)||1;
 const xs=points.map((p,i)=>[
  i*(w-2)/Math.max(points.length-1,1)+1,
  h-2-(p[1]-lo)*(h-4)/span]);
 return '<svg width="'+w+'" height="'+h+'"><polyline fill="none" '+
  'stroke="#06c" stroke-width="1.5" points="'+
  xs.map(q=>q[0].toFixed(1)+','+q[1].toFixed(1)).join(' ')+'"/></svg>';
}
async function refresh(){
 const s=await (await fetch('/api/status')).json();
 document.getElementById('status').innerHTML =
  '<pre>'+JSON.stringify(s,null,2)+'</pre>';
 const m=await (await fetch('/api/metrics')).json();
 document.getElementById('metrics').innerHTML =
  Object.entries(m).map(([k,pts])=>
   '<span class="spark">'+k+' '+sparkline(pts)+' <span class="v">'+
   pts[pts.length-1][1].toPrecision(4)+'</span></span>').join('')
  || '(no epoch metrics yet)';
 const e=await (await fetch('/api/events')).json();
 document.getElementById('events').innerHTML =
  '<pre>'+e.slice(-30).map(x=>JSON.stringify(x)).join('\\n')+'</pre>';
}
refresh(); setInterval(refresh, 3000);
</script></body></html>"""


class WebStatusServer(Logger):
    def __init__(self, host="127.0.0.1", port=8090):
        super(WebStatusServer, self).__init__()
        self.host, self.port = host, port
        self._workflows = {}
        self._updates = []
        self._server = None
        self._thread = None
        self._lock = threading.Lock()

    def register(self, workflow):
        """Track a local workflow; its gather_results() feeds /api/status."""
        with self._lock:
            self._workflows[workflow.name] = workflow

    def metrics(self, limit=200):
        """Per-epoch metric time series from the event ring: every
        numeric field of an ``epoch`` event becomes
        {series: [[epoch, value], ...]} — the dashboard's sparklines
        (ref the node.js status app's live charts, web/)."""
        skip = {"name", "cat", "type", "time", "epoch"}
        series = {}
        for ev in events.snapshot():
            if ev.get("name") != "epoch":
                continue
            ep = ev.get("epoch", 0)
            for k, v in ev.items():
                # non-finite values would serialize as the literal NaN,
                # which strict browser-side JSON.parse rejects
                if (k not in skip and isinstance(v, (int, float))
                        and math.isfinite(v)):
                    series.setdefault(k, []).append([ep, v])
        return {k: v[-limit:] for k, v in series.items()}

    def status(self):
        out = {"time": time.time(), "workflows": {}, "remote": self._updates[-20:]}
        with self._lock:
            for name, wf in self._workflows.items():
                try:
                    out["workflows"][name] = wf.gather_results()
                except Exception as e:  # noqa: BLE001
                    out["workflows"][name] = {"error": str(e)}
        return out

    def start(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code, body, ctype="application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/":
                    self._send(200, _PAGE.encode(), "text/html")
                elif self.path == "/api/status":
                    self._send(200, json.dumps(server.status(),
                                               default=str).encode())
                elif self.path == "/api/events":
                    self._send(200, json.dumps(events.snapshot()[-200:],
                                               default=str).encode())
                elif self.path == "/api/metrics":
                    self._send(200, json.dumps(server.metrics(),
                                               default=str).encode())
                elif self.path == "/api/plots":
                    self._send(200, json.dumps(bus.snapshot()[-20:],
                                               default=str).encode())
                elif self.path == "/frontend":
                    # the command-composer page, generated live from the
                    # CLI arg registry (ref --frontend, launcher.py:199-267)
                    from veles_tpu.scripts import generate_frontend as gf
                    page = gf.render(gf.describe_parser(gf._main_parser()))
                    self._send(200, page.encode(), "text/html")
                else:
                    self.send_error(404)

            def do_POST(self):
                # remote master update (ref web_status '/update' POST)
                if self.path != "/update":
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    update = json.loads(self.rfile.read(length))
                except ValueError:
                    self._send(400, b'{"error": "bad json"}')
                    return
                with server._lock:
                    server._updates.append(
                        {"time": time.time(), "update": update})
                self._send(200, b'{"ok": true}')

            def log_message(self, fmt, *args):
                server.debug("http: " + fmt, *args)

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        self.info("web status on http://%s:%d/", self.host, self.port)

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server = None
