"""Auto-vivifying configuration tree (ref: veles/config.py:60-308).

``root`` is a process-global :class:`Config` tree.  Reading a missing attribute
vivifies a child node, so workflows can write ``root.mnist.learning_rate = 0.1``
without declaring the path first.  Layered overrides mirror the reference:
defaults (this module) < site config < per-run config file < CLI ``--config-list``
statements — later layers win via :meth:`Config.update`.

Differences from the reference, by design:
  * precision is expressed as a dtype *policy* (compute/accum/param dtypes) —
    the reference's Kahan/multipartial ``precision_level`` (veles/config.py:246)
    maps onto "accumulate in f32 over bf16 inputs" on TPU;
  * engine.backend defaults to whatever ``jax.devices()`` provides.
"""

import os
import pprint


class Config(object):
    """One node of the configuration tree."""

    def __init__(self, path):
        self.__dict__["_path_"] = path

    def __getattr__(self, name):
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        child = Config("%s.%s" % (self._path_, name))
        self.__dict__[name] = child
        return child

    def __setattr__(self, name, value):
        self.__dict__[name] = value

    def __delattr__(self, name):
        del self.__dict__[name]

    def __contains__(self, name):
        return name in self.__dict__

    def __iter__(self):
        for k, v in sorted(self.__dict__.items()):
            if k != "_path_":
                yield k, v

    def update(self, value):
        """Deep-merge a dict (or another Config) into this node.

        Mirrors ref veles/config.py:90-116: nested dicts recurse, everything
        else overwrites the leaf.
        """
        if isinstance(value, Config):
            value = value.as_dict()
        if not isinstance(value, dict):
            raise TypeError(
                "Config.update() takes a dict, got %s" % type(value))
        for k, v in value.items():
            if isinstance(v, dict):
                node = self.__dict__.get(k)
                if not isinstance(node, Config):
                    # widening a scalar leaf into a subtree: vivify fresh node
                    node = Config("%s.%s" % (self._path_, k))
                    self.__dict__[k] = node
                node.update(v)
            else:
                setattr(self, k, v)
        return self

    def get(self, name, default=None):
        """Return the attribute if it was explicitly set, else ``default``.

        Unlike plain attribute access this never vivifies a node.
        """
        v = self.__dict__.get(name, default)
        return default if isinstance(v, Config) and not v.as_dict() else v

    def as_dict(self):
        out = {}
        for k, v in self:
            out[k] = v.as_dict() if isinstance(v, Config) else v
        return out

    def print_(self, indent=0, stream=None):
        """Pretty-print the subtree (ref veles/config.py:128-149)."""
        import sys
        stream = stream or sys.stdout
        stream.write("%s:\n" % self._path_)
        pprint.pprint(self.as_dict(), stream=stream)

    def __repr__(self):
        return "<Config %s: %s>" % (self._path_, self.as_dict())


#: The global configuration tree (ref veles/config.py:152).
root = Config("root")


def get(cfg, default=None):
    """Resolve a config leaf: unset Config nodes collapse to ``default``."""
    if isinstance(cfg, Config):
        return default
    return cfg


def _default_dirs():
    base = os.environ.get("VELES_TPU_HOME",
                          os.path.join(os.path.expanduser("~"), ".veles_tpu"))
    return {
        "base": base,
        "cache": os.path.join(base, "cache"),
        "snapshots": os.path.join(base, "snapshots"),
        "datasets": os.environ.get("VELES_TPU_DATA",
                                   os.path.join(base, "datasets")),
    }


# Defaults (ref veles/config.py:178-291).
root.common.update({
    "dirs": _default_dirs(),
    "engine": {
        # "tpu" | "cpu" | "auto": mesh construction consults this
        "backend": os.environ.get("VELES_TPU_BACKEND", "auto"),
        # dtype policy replacing the reference's precision_type/precision_level
        "precision": {
            "compute": "bfloat16",   # MXU-native multiplies
            "accum": "float32",      # accumulation / loss / optimizer math
            "param": "float32",      # master weights
        },
        # precision_level parity knob: 0 => compute dtype as-is,
        # 1/2 => force float32 compute (Kahan/multipartial equivalent on TPU)
        "precision_level": 0,
    },
    "random_seed": 1234,
    "timings": False,
    # crash-consistent checkpointing (services.snapshotter,
    # docs/distributed_training.md "Preemption-safe training"):
    # keep_last bounds the on-disk checkpoint ring per prefix (0 =
    # unlimited); manifest=True writes a per-leaf checksum sidecar
    # validated on restore so torn commits are detected and skipped;
    # commit_retries/retry_backoff_ms retry transient filesystem
    # errors during the commit write before surfacing.
    # per_host=True (the pod tier): EVERY process writes its own full
    # checkpoint copy into its own host-local snapshot directory
    # instead of only process 0 — the substrate the pod master's
    # cross-host checkpoint agreement runs over.
    # reject_nonfinite: commit-time poison valve — a checkpoint whose
    # params/velocity contain NaN/inf is REFUSED (loud death of this
    # life) so the restart loops can never faithfully resume a
    # poisoned state; disable for workloads that legitimately
    # checkpoint non-finite leaves.
    "snapshot": {"interval": 1, "min_interval_seconds": 0, "codec": "gz",
                 "keep_last": 5, "manifest": True, "per_host": False,
                 "reject_nonfinite": True,
                 "commit_retries": 3, "retry_backoff_ms": 100},
    # the training supervisor (services.supervisor, `--supervise`):
    # respawn-on-failure with exponential backoff.  Graceful
    # preemptions (exit 75) respawn immediately and unbounded;
    # kills/fault-injections/crashes respawn with backoff and count
    # against max_restarts per window_seconds (crash-loop valve);
    # deterministic_limit consecutive IDENTICAL crashes with zero
    # checkpoint progress give up early — restarting a deterministic
    # bug only burns the restart budget.
    "supervise": {"max_restarts": 8, "window_seconds": 600,
                  "backoff_base_ms": 200, "backoff_max_ms": 30000,
                  "deterministic_limit": 3},
    # chaos/fault-drill knobs (tools/train_chaos.py, tools/pod_chaos.py,
    # tools/numerics_chaos.py):
    # unit_delay_ms sleeps per scheduler unit-run so external kills land
    # mid-sweep; with unit_delay_file set the sleep additionally
    # requires that file to EXIST, letting a harness switch a long
    # stall on mid-run (the pod chaos gate's forged collective hang).
    # nan_grads_step poisons the gradient tree with NaN at exactly that
    # staged train step (transient numeric fault); nan_grads_from
    # poisons every step >= that counter (persistent divergence) — both
    # are build-time gates inside the jitted step, zero cost when unset
    # (the numerics-chaos gate's injection hooks).
    "chaos": {"unit_delay_ms": 0, "unit_delay_file": None,
              "nan_grads_step": None, "nan_grads_from": None},
    # the numeric-fault survival tier (services.sentinel,
    # docs/distributed_training.md "Numeric-fault survival"): cheap
    # in-jit health probes fused into the staged train step —
    # loss/grad-norm finiteness, EWMA loss-spike z-score, update-norm
    # explosion — read back at the existing read_class_stats sync
    # point (no extra device sync per step), driving a three-rung
    # response ladder: (1) in-jit skip-update of a poisoned step via
    # select (bit-deterministic), (2) after strikes_to_rollback
    # anomalous sweeps, automatic rollback to the last HEALTHY commit
    # plus deterministic replay that skips the poisoned global
    # minibatch (the skip list rides max_skip_steps traced slots, so
    # growing it never recompiles), (3) after rollbacks_to_escalate
    # rollbacks with an identical anomaly signature, escalate with a
    # numerics:<kind> crash class the supervisor/pod master classify
    # under the deterministic-bug valve instead of crash-looping.
    # spike_zscore/spike_warmup tune the EWMA loss-spike probe (the
    # z threshold only fires after warmup observations);
    # update_norm_limit bounds the global update L2 norm (explosion);
    # force_skip_steps pre-loads the skip list (the numerics-chaos
    # golden-skip leg); rollback=False degrades rung 2 to escalation
    # (pods always escalate: pod-scope rollback rides the coordinated
    # restart, whose checkpoint agreement prefers healthy commits).
    "sentinel": {"enabled": True, "strikes_to_rollback": 1,
                 "rollbacks_to_escalate": 3, "spike_zscore": 12.0,
                 "spike_warmup": 64, "update_norm_limit": 1e6,
                 "ewma_decay": 0.99, "max_skip_steps": 8,
                 "force_skip_steps": (), "rollback": True},
    # the pod survival tier (services.podmaster, `veles-tpu-pod`):
    # a pod master coordinates one per-host supervisor agent per host.
    # Agents heartbeat every heartbeat_ms; an agent silent for
    # stale_after_ms, a worker death on ANY host, or no step/commit
    # progress pod-wide for hang_seconds (the collective-hang latch —
    # survivors of a dead/stalled host don't crash, they hang in the
    # next collective) all trigger ONE coordinated pod restart:
    # every agent escalates SIGTERM -> (kill_grace_ms) -> SIGKILL on
    # its worker, the restart checkpoint is computed by cross-host
    # agreement over the per-host integrity manifests
    # (snapshot.per_host), and workers respawn under a new fenced
    # incarnation id (stale registrations are refused).  PR 8's valves
    # lifted to pod scope: max_restarts bounded restarts per
    # window_seconds, deterministic_limit identical pod-wide crash
    # signatures with zero agreed-checkpoint progress give up early.
    # The ELASTIC tier: with elastic=True a host whose agent misses
    # loss_strikes consecutive agreement windows (loss_window_s each)
    # is classified permanently lost and the pod DEGRADES to the
    # survivors (resized mesh, resharded checkpoint) instead of
    # retrying the dead topology; reexpand=True folds the host back in
    # with one re-expand restart when its agent re-registers, shipping
    # the agreed commit to its frozen ring over the control plane
    # (capped at replicate_max_mb — shared-storage pods never need the
    # transfer).  Degrade/re-expand restarts count in their own valve
    # bucket, never the crash-loop or deterministic budget.
    # elastic_mesh is threaded into WORKERS by the master: the
    # launcher then rebuilds a fixed --mesh from the live device set
    # (parallel.mesh.fit_axes_to_devices).
    "pod": {"heartbeat_ms": 500, "stale_after_ms": 10000,
            "hang_seconds": 300, "kill_grace_ms": 5000,
            "max_restarts": 8, "window_seconds": 600,
            "deterministic_limit": 3,
            "backoff_base_ms": 200, "backoff_max_ms": 10000,
            "elastic": True, "loss_strikes": 2, "loss_window_s": 60,
            "reexpand": True, "replicate_max_mb": 64,
            "elastic_mesh": False},
    # status/benchmark web UI (services.web_status): host/port are the
    # WebStatusServer defaults (--web-status PORT overrides the port);
    # bench_cache points the benchmark page at a measurement store
    # (None = the repo-root cache next to bench.py)
    "web": {"host": "127.0.0.1", "port": 8090, "bench_cache": None},
    # telemetry thresholds (telemetry.mfu): warn when measured MFU
    # falls below this fraction of the roofline prediction
    "telemetry": {"mfu_warn_fraction": 0.5},
    # the persistent performance ledger + regression sentinel
    # (telemetry.ledger, docs/perf.md "Performance ledger & regression
    # sentinel").  ledger: explicit JSONL path (None = the
    # VELES_TPU_PERF_LEDGER env var, else <dirs.cache>/
    # perf_ledger.jsonl); enabled gates the automatic trainer/MFU/
    # harness appends; min_history is the fewest prior records before
    # the sentinel bands a key; the band is
    # band_mads x 1.4826 x MAD, floored at min_rel_band of the
    # median; history caps the records read back per key.
    "perf": {"ledger": None, "enabled": True, "min_history": 3,
             "band_mads": 4.0, "min_rel_band": 0.05, "history": 64},
    # the flight recorder / crash forensics / watchdog layer
    # (veles_tpu.telemetry.flight + .health, docs/services.md "Black
    # box").  watchdog_seconds: None = unset (standalone stays
    # disarmed, spmd arms at spmd_watchdog_seconds); an EXPLICIT 0
    # disarms even spmd runs.
    "blackbox": {"capacity": 4096, "dir": "artifacts",
                 "watchdog_seconds": None,
                 "spmd_watchdog_seconds": 300},
    # request tracing (veles_tpu.telemetry.tracing, docs/services.md
    # "Request tracing"): the per-process bounded span store behind
    # /api/trace/<id> and the veles-tpu-trace CLI.  capacity bounds
    # distinct traces held (oldest trace evicted past it), max_spans
    # bounds spans per trace; both evictions are counted
    # (veles_trace_dropped_total).  enabled=False stops span recording
    # entirely (trace ids still propagate on headers/flight events, so
    # post-mortem reconstruction keeps working).
    "trace": {"enabled": True, "capacity": 1024, "max_spans": 128},
    # serving survival layer (services.lifecycle + ContinuousEngine,
    # docs/services.md "Serving robustness").  slo_queue_wait_ms > 0
    # turns breaches from recorded (flight serve.slo_breach) into
    # enforced: the closed-loop shedder rejects new work with 503 +
    # Retry-After past the SLO and reopens below shed_close_fraction
    # of it.  default_deadline_ms > 0 gives every request a deadline
    # (per-request "deadline_ms" overrides); expired requests are
    # cancelled — mid-decode if needed — instead of decoded uselessly.
    # stream_queue_chunks bounds each streaming request's token
    # channel; stream_overflow picks what happens when the consumer
    # falls behind: 'drop_oldest' (default — the terminal line still
    # carries the full result) or 'block' (per-request backpressure:
    # chunks are held back until the consumer drains; a request that
    # makes no progress for stream_stall_timeout_ms is cancelled as a
    # slowloris).
    "serve": {
        "slo_queue_wait_ms": 0,
        "default_deadline_ms": 0,
        # segmented prefill admission (docs/services.md "Disaggregated
        # prefill"): prefill_segment > 0 splits a long prompt's
        # admission prefill into bounded chunk passes of at most this
        # many tokens, interleaved with decode ticks, so one long
        # admission can no longer stall every in-flight decode stream
        # for its whole prompt.  Outputs are byte-identical to the
        # unsegmented path (the chunk resume math is the prefix-cache
        # resume's).  0 = off (whole-prompt prefill at admission).
        # prefill_tick_budget caps the prefill tokens advanced per
        # engine tick across ALL staging admissions (0 = one segment).
        "prefill_segment": 0,
        "prefill_tick_budget": 0,
        "stream_queue_chunks": 64,
        "stream_overflow": "drop_oldest",
        "stream_stall_timeout_ms": 10000,
        "shed_close_fraction": 0.5,
        # retry_after_overshoot_cap bounds how far the 503 Retry-After
        # hint scales with the measured queue-wait overshoot: a replica
        # whose queue wait sits at 4x the SLO tells clients to back off
        # 4 SLO windows (capped here) instead of the flat minimum.
        "retry_after_overshoot_cap": 8.0,
        # graceful drain (services.lifecycle.DrainState): a draining
        # endpoint stops admitting (503 + Retry-After), finishes every
        # in-flight request, then reports "drained" on {path}/health —
        # standalone serve processes drain on SIGTERM and exit 0, fleet
        # replicas drain and get deregistered by the router.
        # drain_timeout_ms caps how long in-flight work may take before
        # the drain is forced through anyway.
        "drain_timeout_ms": 30000,
        # replica fleet tier (services.router.FleetRouter,
        # docs/services.md "Fleet serving"): a front-end router owns N
        # engine replicas, health-checks them every health_interval_ms
        # off each replica's {path}/health surface, and routes with
        # session affinity ("session": same session key sticks to one
        # replica so its prefix cache keeps hitting; "none": round-
        # robin).  A dead replica is retried onto a survivor up to
        # retry_max times with exponential backoff (backoff_base_ms
        # doubling per attempt, capped at backoff_max_ms, jittered);
        # stream_read_timeout_ms bounds one upstream read before the
        # router treats the replica as stalled and fails over; a
        # BUFFERED request produces no bytes until its whole decode
        # finishes, so it gets its own request_timeout_ms budget
        # (default 5 min) instead of the per-chunk one.
        "fleet": {
            "health_interval_ms": 100,
            "retry_max": 3,
            "backoff_base_ms": 20,
            "backoff_max_ms": 2000,
            "affinity": "session",
            "stream_read_timeout_ms": 30000,
            "request_timeout_ms": 300000,
            # --- the autoscaling fleet spec (services.podmaster
            # ServeFleetMaster, `veles-tpu-pod --serve`, docs/
            # services.md "Autoscaling fleet"): the pod master owns
            # the serving replicas declaratively — min..max engine
            # replicas fleet-wide, at most per_host on any one host;
            # agents spawn/drain them and the master auto-registers/
            # deregisters each with its FleetRouter.
            "min": 1,
            "max": 8,
            "per_host": 2,
            # --- prefill/decode fleet roles (docs/services.md
            # "Disaggregated prefill"): prefill_replicas > 0 reserves
            # that many of the desired replicas as PREFILL-role —
            # requests whose prompt length >= prefill_prompt_min are
            # routed there first for the heavy admission prefill plus
            # the first prefill_handoff_new tokens, then continue on a
            # decode-role replica via the same prefix-resume splice
            # the failover path uses (the client sees ONE
            # byte-identical stream).  0 = no role split.
            "prefill_replicas": 0,
            "prefill_prompt_min": 64,
            "prefill_handoff_new": 4,
            # --- placement: "cost" prices every request as predicted
            # prefill work (prompt_len x per-token prefill cost, from
            # tools/cost_model device constants calibrated against the
            # fleet's measured ms/tok) plus predicted decode residency
            # (max_new x measured ms/tok) and routes to the replica
            # with the least outstanding predicted work;
            # "round_robin" keeps the PR 7 rotation.  Session
            # affinity still wins over either.
            "placement": "cost",
            # --- the autoscaler loop: scale UP when any replica's
            # measured queue-wait overshoot (SloShedder.overshoot,
            # read off /health) reaches scale_up_overshoot or fresh
            # serve.shed rejections arrive; scale DOWN after
            # scale_idle_s of fleet-wide idle (always through the
            # SIGTERM drain, so scale-down is lossless by
            # construction).  scale_cooldown_s spaces consecutive
            # decisions; on top of that every decision is budgeted in
            # its own PodValves bucket (scale_max_per_window per
            # scale_window_s — flap damping: a scale oscillation can
            # never consume the crash-loop budget).
            "scale_up_overshoot": 1.0,
            # scale UP early when the fleet-wide queued-but-unprefilled
            # prompt backlog (replica queued_prefill_tokens, summed by
            # FleetRouter.fleet_signals) reaches this many tokens —
            # prefill backlog predicts the queue-wait breach before
            # the shedder can measure it.  0 disables the signal.
            "scale_up_prefill_backlog": 4096,
            "scale_idle_s": 30.0,
            "scale_cooldown_s": 10.0,
            "scale_window_s": 120.0,
            "scale_max_per_window": 4,
            # a spawned replica must announce READY (bound port)
            # within this budget or the spawn is classified a crash
            # and replaced — a wedged replica must not hold a fleet
            # slot forever
            "ready_timeout_ms": 180000,
            # a replica must stay up this long (or serve a request)
            # before its next crash counts as "progressed" for the
            # deterministic-bug valve — mirrors the training
            # supervisor's checkpoint-progress reset
            "min_uptime_s": 30.0,
        },
    },
})


def apply_site_config():
    """Site override chain (ref veles/config.py:294-308): import
    ``veles_tpu_site_config`` if present and call its ``update(root)``."""
    try:
        import veles_tpu_site_config  # noqa: F401
    except ImportError:
        return
    if hasattr(veles_tpu_site_config, "update"):
        veles_tpu_site_config.update(root)


apply_site_config()
