"""Lazy boolean expression graph used for unit gates (ref: veles/mutable.py).

``Bool`` (ref mutable.py:44) is a mutable truth cell that composes lazily:
``gate = a & ~b`` builds an expression over *live* references to ``a`` and
``b``, so flipping either source later changes the gate's truth.  Units use
these for ``gate_block`` / ``gate_skip`` and Decision wiring — all host-side
control, never traced into XLA."""


class Bool(object):
    __slots__ = ("_value", "_expr", "_name")

    def __init__(self, value=False, _expr=None, _name=None):
        self._value = bool(value)
        self._expr = _expr       # callable() -> bool, for derived Bools
        self._name = _name

    # -- assignment ----------------------------------------------------------
    def __ilshift__(self, value):
        """``b <<= True`` — assign a new truth value (ref mutable.py:100)."""
        if self._expr is not None:
            raise ValueError("cannot assign to a derived Bool (%s)" % self)
        self._value = bool(value)
        return self

    def set(self, value):
        self.__ilshift__(value)

    # -- evaluation ----------------------------------------------------------
    def __bool__(self):
        if self._expr is not None:
            return self._expr()
        return self._value

    __nonzero__ = __bool__

    # -- lazy composition ----------------------------------------------------
    def __and__(self, other):
        return Bool(_expr=lambda: bool(self) and bool(other), _name="&")

    def __or__(self, other):
        return Bool(_expr=lambda: bool(self) or bool(other), _name="|")

    def __xor__(self, other):
        return Bool(_expr=lambda: bool(self) != bool(other), _name="^")

    def __invert__(self):
        return Bool(_expr=lambda: not bool(self), _name="~")

    def __repr__(self):
        kind = "derived(%s)" % self._name if self._expr else "value"
        return "<Bool %s = %s>" % (kind, bool(self))


class LinkableAttribute(object):
    """Descriptor that forwards an attribute to another object's attribute
    (ref mutable.py:219-353).  ``link(dst, "a", src, "b")`` makes ``dst.a``
    read/write ``src.b``.  Unit.link_attrs builds on the same mechanism via
    its own per-instance table; this class serves plain objects."""

    def __init__(self, src, src_attr, two_way=True):
        self._src = src
        self._src_attr = src_attr
        self._two_way = two_way

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return getattr(self._src, self._src_attr)

    def __set__(self, obj, value):
        if not self._two_way:
            raise AttributeError(
                "one-way linked attribute -> %s.%s is read-only"
                % (type(self._src).__name__, self._src_attr))
        setattr(self._src, self._src_attr, value)


def link(dst, dst_attr, src, src_attr=None, two_way=True):
    """Install a LinkableAttribute on ``type(dst)`` under ``dst_attr``
    forwarding to ``src.src_attr`` (ref mutable.py:353).  The descriptor is
    installed on a per-instance shadow subclass so other instances of the
    class are unaffected."""
    src_attr = src_attr or dst_attr
    cls = type(dst)
    if not getattr(cls, "_linkable_shadow_", False):
        cls = type(cls.__name__, (cls,), {"_linkable_shadow_": True})
        dst.__class__ = cls
    setattr(cls, dst_attr, LinkableAttribute(src, src_attr, two_way))
