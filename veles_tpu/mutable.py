"""Lazy boolean expression graph used for unit gates (ref: veles/mutable.py).

``Bool`` (ref mutable.py:44) is a mutable truth cell that composes lazily:
``gate = a & ~b`` builds an expression over *live* references to ``a`` and
``b``, so flipping either source later changes the gate's truth.  Units use
these for ``gate_block`` / ``gate_skip`` and Decision wiring — all host-side
control, never traced into XLA."""


class Bool(object):
    __slots__ = ("_value", "_expr", "_name", "_operands")

    def __init__(self, value=False, _expr=None, _name=None, _operands=()):
        self._value = bool(value)
        self._expr = _expr       # callable() -> bool, for derived Bools
        self._name = _name       # operator symbol for derived Bools
        #: structural metadata: the source operands of a derived Bool
        #: (Bools or plain truth values).  Lets static analysis (the
        #: workflow linter, veles_tpu.analysis) see through derived
        #: gates instead of hitting an opaque lambda.
        self._operands = _operands

    # -- assignment ----------------------------------------------------------
    def __ilshift__(self, value):
        """``b <<= True`` — assign a new truth value (ref mutable.py:100)."""
        if self._expr is not None:
            raise ValueError("cannot assign to a derived Bool (%s)" % self)
        self._value = bool(value)
        return self

    def set(self, value):
        self.__ilshift__(value)

    # -- evaluation ----------------------------------------------------------
    def __bool__(self):
        if self._expr is not None:
            return self._expr()
        return self._value

    __nonzero__ = __bool__

    # -- lazy composition ----------------------------------------------------
    def __and__(self, other):
        return Bool(_expr=lambda: bool(self) and bool(other), _name="&",
                    _operands=(self, other))

    def __or__(self, other):
        return Bool(_expr=lambda: bool(self) or bool(other), _name="|",
                    _operands=(self, other))

    def __xor__(self, other):
        return Bool(_expr=lambda: bool(self) != bool(other), _name="^",
                    _operands=(self, other))

    def __invert__(self):
        return Bool(_expr=lambda: not bool(self), _name="~",
                    _operands=(self,))

    # -- structural inspection (consumed by veles_tpu.analysis) --------------
    @property
    def derived(self):
        """True for expression Bools (``a & ~b``), False for value cells."""
        return self._expr is not None

    @property
    def op(self):
        """Operator symbol of a derived Bool (``&``/``|``/``^``/``~``),
        None for value cells."""
        return self._name if self._expr is not None else None

    @property
    def operands(self):
        """Source operands of a derived Bool (empty for value cells)."""
        return self._operands

    def leaves(self):
        """All distinct value-cell Bools this expression is rooted in (the
        Bool itself for a value cell).  A leaf shared between operands
        (``a | ~a``) appears once — it is one variable, not two.  Non-Bool
        operands are skipped — they are immutable truth constants as far
        as the expression goes."""
        if self._expr is None:
            return [self]
        out = []
        seen = set()
        for op in self._operands:
            if not isinstance(op, Bool):
                continue
            for leaf in op.leaves():
                if id(leaf) not in seen:
                    seen.add(id(leaf))
                    out.append(leaf)
        return out

    def expression(self):
        """Human-readable structural rendering of the gate expression,
        e.g. ``(complete & ~epoch_ended)`` rendered with current leaf
        truth values: ``(False & ~True)``."""
        if self._expr is None:
            return str(self._value)

        def render(op):
            return op.expression() if isinstance(op, Bool) \
                else str(bool(op))

        if self._name == "~" and len(self._operands) == 1:
            return "~%s" % render(self._operands[0])
        if len(self._operands) == 2:
            return "(%s %s %s)" % (render(self._operands[0]), self._name,
                                   render(self._operands[1]))
        # derived Bool constructed directly with a bare _expr (no
        # structural metadata) — all we can show is the operator tag
        return "<%s>" % (self._name or "expr")

    def __repr__(self):
        if self._expr is not None:
            return "<Bool %s = %s>" % (self.expression(), bool(self))
        return "<Bool value = %s>" % bool(self)


class LinkableAttribute(object):
    """Descriptor that forwards an attribute to another object's attribute
    (ref mutable.py:219-353).  ``link(dst, "a", src, "b")`` makes ``dst.a``
    read/write ``src.b``.  Unit.link_attrs builds on the same mechanism via
    its own per-instance table; this class serves plain objects."""

    def __init__(self, src, src_attr, two_way=True):
        self._src = src
        self._src_attr = src_attr
        self._two_way = two_way

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return getattr(self._src, self._src_attr)

    def __set__(self, obj, value):
        if not self._two_way:
            raise AttributeError(
                "one-way linked attribute -> %s.%s is read-only"
                % (type(self._src).__name__, self._src_attr))
        setattr(self._src, self._src_attr, value)


def link(dst, dst_attr, src, src_attr=None, two_way=True):
    """Install a LinkableAttribute on ``type(dst)`` under ``dst_attr``
    forwarding to ``src.src_attr`` (ref mutable.py:353).  The descriptor is
    installed on a per-instance shadow subclass so other instances of the
    class are unaffected."""
    src_attr = src_attr or dst_attr
    cls = type(dst)
    if not getattr(cls, "_linkable_shadow_", False):
        cls = type(cls.__name__, (cls,), {"_linkable_shadow_": True})
        dst.__class__ = cls
    setattr(cls, dst_attr, LinkableAttribute(src, src_attr, two_way))
