"""Class registries (ref: veles/unit_registry.py:51-178, veles/normalization.py:110).

``UnitRegistry`` is a metaclass recording every concrete Unit subclass for
introspection, the CLI frontend, and the forge/model-zoo.  ``MappedRegistry``
adds a MAPPING-name → class dictionary used by loaders, normalizers,
snapshot codecs, and publishers."""


class UnitRegistry(type):
    """Metaclass keeping the set of all registered unit classes
    (ref unit_registry.py:51)."""

    units = set()

    def __init__(cls, name, bases, clsdict):
        super(UnitRegistry, cls).__init__(name, bases, clsdict)
        if not clsdict.get("hide_from_registry", False):
            UnitRegistry.units.add(cls)

    @staticmethod
    def find(name):
        for cls in UnitRegistry.units:
            if cls.__name__ == name:
                return cls
        raise KeyError("no registered unit class named %r" % name)


class MappedRegistry(type):
    """Metaclass building a name→class map per registry family
    (ref unit_registry.py:178).  Subclass families set ``MAPPING = "name"``
    on each concrete class; the family root carries ``mapping = {}``."""

    def __init__(cls, name, bases, clsdict):
        super(MappedRegistry, cls).__init__(name, bases, clsdict)
        mapping = None
        for base in cls.__mro__:
            if "mapping" in base.__dict__:
                mapping = base.__dict__["mapping"]
                break
        if mapping is None:
            cls.mapping = {}
            return
        key = clsdict.get("MAPPING")
        if key:
            mapping[key] = cls

    def __getitem__(cls, key):
        return cls.mapping[key]

    def __contains__(cls, key):
        return key in cls.mapping
