"""Utility scripts (ref veles/scripts/ — SURVEY.md §2.11):
``compare_snapshots`` (diff two checkpoints), ``generate_frontend``
(HTML command composer generated from the CLI arg registry), ``bboxer``
(bounding-box annotation, headless CLI here)."""
