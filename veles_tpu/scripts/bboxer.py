"""Bounding-box annotation tool (ref veles/scripts/bboxer.py).

The reference ships an interactive GUI annotator; this provides BOTH a
headless CLI (drivable from scripts/CI) and a browser-canvas annotator
(``serve``) over the SAME artifact — a JSON annotations file consumable
by the image loaders.  The GUI is a zero-dependency local web page:
click-drag draws a box, a prompt labels it, click an entry deletes it.

Commands:
  add <store.json> <image> <label> <x> <y> <w> <h>
  list <store.json> [image]
  export <store.json> <out.json>     # loader-friendly {image: [boxes]}
  remove <store.json> <image> <index>
  serve <store.json> <images_dir> [--host H] [--port P]
"""

import argparse
import json
import os
import sys


def _load(store):
    if os.path.exists(store):
        with open(store) as f:
            return json.load(f)
    return {"annotations": {}}


def _save(store, db):
    with open(store, "w") as f:
        json.dump(db, f, indent=2, sort_keys=True)


def add(store, image, label, x, y, w, h):
    if min(w, h) <= 0:
        raise ValueError("box must have positive size")
    db = _load(store)
    db["annotations"].setdefault(image, []).append(
        {"label": label, "x": x, "y": y, "w": w, "h": h})
    _save(store, db)
    return len(db["annotations"][image])


def list_boxes(store, image=None, out=None):
    out = out if out is not None else sys.stdout
    db = _load(store)
    items = (db["annotations"].items() if image is None
             else [(image, db["annotations"].get(image, []))])
    count = 0
    for name, boxes in sorted(items):
        for i, b in enumerate(boxes):
            print("%s[%d]: %s (%g,%g %gx%g)"
                  % (name, i, b["label"], b["x"], b["y"], b["w"], b["h"]),
                  file=out)
            count += 1
    return count

def remove(store, image, index):
    db = _load(store)
    boxes = db["annotations"].get(image, [])
    if not 0 <= index < len(boxes):
        raise IndexError("no box %d for %s" % (index, image))
    boxes.pop(index)
    if not boxes:
        db["annotations"].pop(image)
    _save(store, db)


def export(store, out_path):
    db = _load(store)
    with open(out_path, "w") as f:
        json.dump(db["annotations"], f, indent=2, sort_keys=True)
    return sum(len(v) for v in db["annotations"].values())


_PAGE = """<!doctype html><meta charset="utf-8">
<title>bboxer</title>
<style>
 body{font:14px sans-serif;margin:1em;background:#111;color:#ddd}
 #imgs a{margin-right:.8em;color:#8cf} #imgs a.cur{color:#fc6}
 #wrap{position:relative;display:inline-block;margin-top:.6em}
 canvas{position:absolute;left:0;top:0;cursor:crosshair}
 #boxes li{cursor:pointer} #boxes li:hover{color:#f66}
</style>
<div id=imgs></div>
<div id=wrap><img id=im><canvas id=cv></canvas></div>
<ol id=boxes></ol>
<script>
let cur=null, boxes=[], drag=null;
const im=document.getElementById('im'), cv=document.getElementById('cv'),
      ctx=cv.getContext('2d');
async function j(u,opt){return (await fetch(u,opt)).json()}
async function imgs(){
  const names=await j('/api/images'); const d=document.getElementById('imgs');
  d.innerHTML=''; for(const n of names){const a=document.createElement('a');
    a.textContent=n; a.href='#'; a.className=n===cur?'cur':'';
    a.onclick=e=>{e.preventDefault();pick(n)}; d.appendChild(a);}
  if(!cur&&names.length)pick(names[0]);}
async function pick(n){cur=n; im.src='/img/'+encodeURIComponent(n);
  im.onload=()=>{cv.width=im.width; cv.height=im.height; refresh()}; imgs();}
function draw(){
  ctx.clearRect(0,0,cv.width,cv.height); ctx.lineWidth=2;
  const ol=document.getElementById('boxes'); ol.innerHTML='';
  boxes.forEach((b,i)=>{ctx.strokeStyle='#fc6';
    ctx.strokeRect(b.x,b.y,b.w,b.h); ctx.fillStyle='#fc6';
    ctx.fillText(b.label,b.x+3,b.y+12);
    const li=document.createElement('li');
    li.textContent=b.label+' ('+b.x+','+b.y+' '+b.w+'x'+b.h+') — click to delete';
    li.onclick=async()=>{await j('/api/remove',{method:'POST',
      body:JSON.stringify({image:cur,index:i})}); refresh()};
    ol.appendChild(li);});}
async function refresh(){
  boxes=await j('/api/annotations?image='+encodeURIComponent(cur));
  draw();}
cv.onmousedown=e=>{drag=[e.offsetX,e.offsetY]};
cv.onmousemove=e=>{if(!drag)return; draw();  // local redraw, no fetch
  ctx.strokeStyle='#6f6';
  ctx.strokeRect(drag[0],drag[1],e.offsetX-drag[0],e.offsetY-drag[1])};
cv.onmouseup=async e=>{if(!drag)return;
  const x=Math.min(drag[0],e.offsetX), y=Math.min(drag[1],e.offsetY),
        w=Math.abs(e.offsetX-drag[0]), h=Math.abs(e.offsetY-drag[1]);
  drag=null; if(w<3||h<3)return refresh();
  const label=prompt('label for this box?','object'); if(!label)return refresh();
  await j('/api/add',{method:'POST',
    body:JSON.stringify({image:cur,label:label,x:x,y:y,w:w,h:h})});
  refresh()};
imgs();
</script>"""

_IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".gif", ".bmp", ".webp")


def serve(store, images_dir, host="127.0.0.1", port=8088,
          server_cls=None):
    """Browser-canvas annotator over the CLI's exact store functions —
    the interactive counterpart of the reference's GUI (ref
    veles/scripts/bboxer.py) with the same JSON artifact.  Returns the
    server (caller calls serve_forever / shutdown; __main__ runs it)."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    images_dir = os.path.abspath(images_dir)
    store_lock = threading.Lock()   # load-modify-save must not interleave

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):     # quiet server
            pass

        def _send(self, code, body, ctype="application/json"):
            data = body if isinstance(body, bytes) else \
                json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            from urllib.parse import parse_qs, unquote, urlparse
            u = urlparse(self.path)
            if u.path == "/":
                return self._send(200, _PAGE.encode(),
                                  "text/html; charset=utf-8")
            if u.path == "/api/images":
                names = sorted(
                    n for n in os.listdir(images_dir)
                    if n.lower().endswith(_IMAGE_EXTS))
                return self._send(200, names)
            if u.path == "/api/annotations":
                img = parse_qs(u.query).get("image", [""])[0]
                db = _load(store)
                return self._send(200, db["annotations"].get(img, []))
            if u.path.startswith("/img/"):
                name = unquote(u.path[len("/img/"):])
                full = os.path.abspath(os.path.join(images_dir, name))
                # no traversal: the resolved path must stay inside
                if not full.startswith(images_dir + os.sep) or \
                        not os.path.isfile(full):
                    return self._send(404, {"error": "no such image"})
                with open(full, "rb") as f:
                    return self._send(200, f.read(),
                                      "application/octet-stream")
            return self._send(404, {"error": "unknown path"})

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            try:
                req = json.loads(self.rfile.read(n) or b"{}")
                if self.path == "/api/add":
                    with store_lock:
                        cnt = add(store, req["image"],
                                  str(req["label"]),
                                  float(req["x"]), float(req["y"]),
                                  float(req["w"]), float(req["h"]))
                    return self._send(200, {"ok": True, "boxes": cnt})
                if self.path == "/api/remove":
                    with store_lock:
                        remove(store, req["image"], int(req["index"]))
                    return self._send(200, {"ok": True})
            except (KeyError, ValueError, IndexError, TypeError) as e:
                return self._send(400, {"error": str(e)})
            return self._send(404, {"error": "unknown path"})

    return (server_cls or ThreadingHTTPServer)((host, port), Handler)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    pa = sub.add_parser("add")
    for name, typ in (("store", str), ("image", str), ("label", str),
                      ("x", float), ("y", float), ("w", float),
                      ("h", float)):
        pa.add_argument(name, type=typ)
    pl = sub.add_parser("list")
    pl.add_argument("store")
    pl.add_argument("image", nargs="?")
    pe = sub.add_parser("export")
    pe.add_argument("store")
    pe.add_argument("output")
    pr = sub.add_parser("remove")
    pr.add_argument("store")
    pr.add_argument("image")
    pr.add_argument("index", type=int)
    ps = sub.add_parser("serve")
    ps.add_argument("store")
    ps.add_argument("images_dir")
    ps.add_argument("--host", default="127.0.0.1")
    ps.add_argument("--port", type=int, default=8088)
    a = p.parse_args(argv)
    if a.cmd == "add":
        n = add(a.store, a.image, a.label, a.x, a.y, a.w, a.h)
        print("%s: %d boxes" % (a.image, n))
    elif a.cmd == "list":
        list_boxes(a.store, a.image)
    elif a.cmd == "export":
        n = export(a.store, a.output)
        print("exported %d boxes -> %s" % (n, a.output))
    elif a.cmd == "remove":
        remove(a.store, a.image, a.index)
    elif a.cmd == "serve":
        srv = serve(a.store, a.images_dir, a.host, a.port)
        print("bboxer GUI on http://%s:%d (store: %s, images: %s)"
              % (a.host, srv.server_address[1], a.store, a.images_dir))
        try:
            srv.serve_forever()
        except KeyboardInterrupt:
            srv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
