"""Bounding-box annotation tool, headless CLI (ref veles/scripts/bboxer.py
— the reference ships a GUI annotator; this keeps the same artifact, a
JSON annotations file consumable by the image loaders, drivable from
scripts/CI).

Commands:
  add <store.json> <image> <label> <x> <y> <w> <h>
  list <store.json> [image]
  export <store.json> <out.json>     # loader-friendly {image: [boxes]}
  remove <store.json> <image> <index>
"""

import argparse
import json
import os
import sys


def _load(store):
    if os.path.exists(store):
        with open(store) as f:
            return json.load(f)
    return {"annotations": {}}


def _save(store, db):
    with open(store, "w") as f:
        json.dump(db, f, indent=2, sort_keys=True)


def add(store, image, label, x, y, w, h):
    if min(w, h) <= 0:
        raise ValueError("box must have positive size")
    db = _load(store)
    db["annotations"].setdefault(image, []).append(
        {"label": label, "x": x, "y": y, "w": w, "h": h})
    _save(store, db)
    return len(db["annotations"][image])


def list_boxes(store, image=None, out=None):
    out = out if out is not None else sys.stdout
    db = _load(store)
    items = (db["annotations"].items() if image is None
             else [(image, db["annotations"].get(image, []))])
    count = 0
    for name, boxes in sorted(items):
        for i, b in enumerate(boxes):
            print("%s[%d]: %s (%g,%g %gx%g)"
                  % (name, i, b["label"], b["x"], b["y"], b["w"], b["h"]),
                  file=out)
            count += 1
    return count

def remove(store, image, index):
    db = _load(store)
    boxes = db["annotations"].get(image, [])
    if not 0 <= index < len(boxes):
        raise IndexError("no box %d for %s" % (index, image))
    boxes.pop(index)
    if not boxes:
        db["annotations"].pop(image)
    _save(store, db)


def export(store, out_path):
    db = _load(store)
    with open(out_path, "w") as f:
        json.dump(db["annotations"], f, indent=2, sort_keys=True)
    return sum(len(v) for v in db["annotations"].values())


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    pa = sub.add_parser("add")
    for name, typ in (("store", str), ("image", str), ("label", str),
                      ("x", float), ("y", float), ("w", float),
                      ("h", float)):
        pa.add_argument(name, type=typ)
    pl = sub.add_parser("list")
    pl.add_argument("store")
    pl.add_argument("image", nargs="?")
    pe = sub.add_parser("export")
    pe.add_argument("store")
    pe.add_argument("output")
    pr = sub.add_parser("remove")
    pr.add_argument("store")
    pr.add_argument("image")
    pr.add_argument("index", type=int)
    a = p.parse_args(argv)
    if a.cmd == "add":
        n = add(a.store, a.image, a.label, a.x, a.y, a.w, a.h)
        print("%s: %d boxes" % (a.image, n))
    elif a.cmd == "list":
        list_boxes(a.store, a.image)
    elif a.cmd == "export":
        n = export(a.store, a.output)
        print("exported %d boxes -> %s" % (n, a.output))
    elif a.cmd == "remove":
        remove(a.store, a.image, a.index)
    return 0


if __name__ == "__main__":
    sys.exit(main())
