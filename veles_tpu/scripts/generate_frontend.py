"""Generate the HTML command composer from the CLI's argument registry
(ref veles/scripts/generate_frontend.py — builds the ``--frontend``
command-composer page from the scattered argparse registry,
setup.py:87-92).

Walks the real ``Main`` parser, emits a form with one input per option
and a JS snippet assembling the command line live."""

import argparse
import html
import json
import sys

from veles_tpu.__main__ import Main

_PAGE = """<!doctype html><html><head><meta charset="utf-8">
<title>veles_tpu command composer</title>
<style>body{font-family:sans-serif;margin:2em}label{display:block;
margin:.5em 0}input,select{margin-left:.5em}#cmd{background:#eee;
padding:1em;font-family:monospace;white-space:pre-wrap}</style>
</head><body><h1>veles_tpu command composer</h1><form id="f">
%(fields)s</form><h2>Command</h2><div id="cmd"></div>
<script>
const SPEC = %(spec)s;
function build() {
  let cmd = ["python", "-m", "veles_tpu"];
  for (const s of SPEC) {
    const el = document.getElementById(s.id);
    if (!el) continue;
    if (s.kind === "flag") { if (el.checked) cmd.push(s.option); }
    else if (el.value) {
      if (s.option) cmd.push(s.option);
      cmd.push(el.value);
    }
  }
  document.getElementById("cmd").textContent = cmd.join(" ");
}
document.getElementById("f").addEventListener("input", build);
build();
</script></body></html>"""


def describe_parser(parser):
    """argparse parser → list of field specs (shared with the web status
    frontend)."""
    spec = []
    for action in parser._actions:
        if isinstance(action, argparse._HelpAction):
            continue
        kind = ("flag" if isinstance(
            action, (argparse._StoreTrueAction, argparse._CountAction))
            else "positional" if not action.option_strings else "value")
        spec.append({
            "id": "opt_" + action.dest,
            "dest": action.dest,
            "option": action.option_strings[0] if action.option_strings
                      else None,
            "kind": kind,
            "help": action.help or "",
            "default": (None if action.default in (None, argparse.SUPPRESS)
                        else action.default),
        })
    return spec


def render(spec):
    fields = []
    for s in spec:
        label = html.escape(s["dest"])
        title = html.escape(s["help"])
        if s["kind"] == "flag":
            inp = ('<input type="checkbox" id="%s">' % s["id"])
        else:
            default = "" if s["default"] in (None, []) else str(s["default"])
            inp = ('<input type="text" id="%s" value="%s">'
                   % (s["id"], html.escape(default)))
        fields.append('<label title="%s">%s %s</label>' % (title, label, inp))
    return _PAGE % {"fields": "\n".join(fields),
                    "spec": json.dumps(spec)}


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("-o", "--output", default="frontend.html")
    args = p.parse_args(argv)
    main_parser = _main_parser()
    spec = describe_parser(main_parser)
    with open(args.output, "w") as f:
        f.write(render(spec))
    print("wrote %s (%d options)" % (args.output, len(spec)))
    return 0


def _main_parser():
    """The real CLI parser, built but not consumed."""
    return Main([])._build_parser()


if __name__ == "__main__":
    sys.exit(main())
