"""Compare two training snapshots leaf by leaf (ref
veles/scripts/compare_snapshots.py — used with the reproducible-RNG
guarantee to verify bit-identical reruns, SURVEY.md §4).

Doubles as the exactness VERIFIER behind ``tools/train_chaos.py``: the
chaos gate resumes a killed run and asserts its final checkpoint is
bit-identical to an uninterrupted golden run — :func:`diff_report`
returns the machine-readable verdict (``--format json`` on the CLI),
``--ignore PREFIX`` masks leaf subtrees when a looser comparison is
wanted.

Usage: python -m veles_tpu.scripts.compare_snapshots A.pickle.gz B.pickle.gz
Exit code 0 = identical within threshold, 1 = differs."""

import argparse
import json
import sys

import numpy as np

from veles_tpu.numpy_ext import NumDiff
from veles_tpu.services.snapshotter import (SnapshotterBase,
                                            iter_state_leaves)


def diff_report(path_a, path_b, threshold=0.0, ignore=(),
                allow_remote=False):
    """Leaf-by-leaf diff of two snapshots as a machine-readable dict:
    ``{"identical": bool, "n_leaves": int, "diffs": [{"path", "kind",
    "detail"}, ...]}``.  ``ignore`` is a sequence of leaf-path
    prefixes (e.g. ``("/decision",)``) excluded from the verdict."""
    a = dict(iter_state_leaves(SnapshotterBase.import_(
        path_a, allow_remote=allow_remote)))
    b = dict(iter_state_leaves(SnapshotterBase.import_(
        path_b, allow_remote=allow_remote)))
    diffs = []
    n_compared = 0
    for path in sorted(set(a) | set(b)):
        if any(path.startswith(pre) for pre in ignore):
            continue
        if path not in a or path not in b:
            diffs.append({"path": path, "kind": "only_in",
                          "detail": "B" if path not in a else "A"})
            continue
        n_compared += 1
        va, vb = a[path], b[path]
        if isinstance(va, np.ndarray) or isinstance(vb, np.ndarray):
            va, vb = np.asarray(va), np.asarray(vb)
            if va.shape != vb.shape:
                diffs.append({"path": path, "kind": "shape",
                              "detail": "%s vs %s" % (va.shape,
                                                      vb.shape)})
                continue
            if not np.issubdtype(va.dtype, np.number):
                if not (va == vb).all():
                    diffs.append({"path": path, "kind": "diff",
                                  "detail": "non-numeric"})
                continue
            d = NumDiff(threshold=threshold).check(va, vb)
            if not d.ok:
                diffs.append({"path": path, "kind": "diff",
                              "detail": d.report()})
        elif va != vb:
            diffs.append({"path": path, "kind": "diff",
                          "detail": "%r vs %r" % (va, vb)})
    return {"identical": not diffs, "n_leaves": n_compared,
            "threshold": threshold, "diffs": diffs}


def compare(path_a, path_b, threshold=0.0, out=sys.stdout,
            allow_remote=False, ignore=()):
    report = diff_report(path_a, path_b, threshold=threshold,
                         ignore=ignore, allow_remote=allow_remote)
    for d in report["diffs"]:
        if d["kind"] == "only_in":
            print("ONLY IN %s: %s" % (d["detail"], d["path"]), file=out)
        elif d["kind"] == "shape":
            print("SHAPE %s: %s" % (d["path"], d["detail"]), file=out)
        else:
            print("DIFF %s: %s" % (d["path"], d["detail"]), file=out)
    if report["identical"]:
        print("snapshots match (threshold %g, %d leaves)"
              % (threshold, report["n_leaves"]), file=out)
    return 0 if report["identical"] else 1


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("snapshot_a")
    p.add_argument("snapshot_b")
    p.add_argument("--threshold", type=float, default=0.0,
                   help="max tolerated abs elementwise diff")
    p.add_argument("--ignore", action="append", default=[],
                   metavar="PREFIX",
                   help="exclude leaf paths starting with PREFIX "
                   "(repeatable), e.g. --ignore /decision")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="json prints the full machine-readable report")
    p.add_argument("--allow-remote-snapshot", action="store_true",
                   help="opt in to comparing http(s) snapshot URLs "
                   "(pickle import runs code)")
    args = p.parse_args(argv)
    if args.format == "json":
        report = diff_report(args.snapshot_a, args.snapshot_b,
                             threshold=args.threshold,
                             ignore=tuple(args.ignore),
                             allow_remote=args.allow_remote_snapshot)
        json.dump(report, sys.stdout, indent=2)
        print()
        return 0 if report["identical"] else 1
    return compare(args.snapshot_a, args.snapshot_b, args.threshold,
                   ignore=tuple(args.ignore),
                   allow_remote=args.allow_remote_snapshot)


if __name__ == "__main__":
    sys.exit(main())
