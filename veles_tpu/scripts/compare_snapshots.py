"""Compare two training snapshots leaf by leaf (ref
veles/scripts/compare_snapshots.py — used with the reproducible-RNG
guarantee to verify bit-identical reruns, SURVEY.md §4).

Usage: python -m veles_tpu.scripts.compare_snapshots A.pickle.gz B.pickle.gz
Exit code 0 = identical within threshold, 1 = differs."""

import argparse
import sys

import numpy as np

from veles_tpu.numpy_ext import NumDiff
from veles_tpu.services.snapshotter import SnapshotterBase


def _leaves(obj, prefix=""):
    """Flatten nested dict/list/tuple state into (path, leaf) pairs."""
    if isinstance(obj, dict):
        for k in sorted(obj, key=str):
            yield from _leaves(obj[k], "%s/%s" % (prefix, k))
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            yield from _leaves(v, "%s[%d]" % (prefix, i))
    else:
        yield prefix or "/", obj


def compare(path_a, path_b, threshold=0.0, out=sys.stdout,
            allow_remote=False):
    a = dict(_leaves(SnapshotterBase.import_(path_a,
                                             allow_remote=allow_remote)))
    b = dict(_leaves(SnapshotterBase.import_(path_b,
                                             allow_remote=allow_remote)))
    differs = False
    for path in sorted(set(a) | set(b)):
        if path not in a or path not in b:
            print("ONLY IN %s: %s" % ("B" if path not in a else "A", path),
                  file=out)
            differs = True
            continue
        va, vb = a[path], b[path]
        if isinstance(va, np.ndarray) or isinstance(vb, np.ndarray):
            va, vb = np.asarray(va), np.asarray(vb)
            if va.shape != vb.shape:
                print("SHAPE %s: %s vs %s" % (path, va.shape, vb.shape),
                      file=out)
                differs = True
                continue
            if not np.issubdtype(va.dtype, np.number):
                if not (va == vb).all():
                    print("DIFF %s (non-numeric)" % path, file=out)
                    differs = True
                continue
            d = NumDiff(threshold=threshold).check(va, vb)
            if not d.ok:
                print("DIFF %s: %s" % (path, d.report()), file=out)
                differs = True
        elif va != vb:
            print("DIFF %s: %r vs %r" % (path, va, vb), file=out)
            differs = True
    if not differs:
        print("snapshots match (threshold %g)" % threshold, file=out)
    return 1 if differs else 0


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("snapshot_a")
    p.add_argument("snapshot_b")
    p.add_argument("--threshold", type=float, default=0.0,
                   help="max tolerated abs elementwise diff")
    p.add_argument("--allow-remote-snapshot", action="store_true",
                   help="opt in to comparing http(s) snapshot URLs "
                   "(pickle import runs code)")
    args = p.parse_args(argv)
    return compare(args.snapshot_a, args.snapshot_b, args.threshold,
                   allow_remote=args.allow_remote_snapshot)


if __name__ == "__main__":
    sys.exit(main())
