"""Numerics, determinism & Pallas-kernel auditor for the staged step.

The platform's thesis is "catch model-definition mistakes before chip
time" (docs/static_analysis.md): PR 1-2 covered graph/staging (VG/VJ)
and sharding/HBM (VS/VM).  The remaining class of silent, statically
decidable failures is NUMERICAL: a ``log`` fed a value that can reach
zero NaNs the loss on step 40k, a bf16 sum over a long axis quietly
loses 40 dB of signal, two draws from one PRNG key correlate every
dropout mask with the data order, and a hand-tiled Pallas kernel with
a 100-row block pays a 28% retile tax on every copy.  All of them are
visible ahead of time — the jaxpr of the staged step traces over
abstract ``ShapeDtypeStruct`` inputs (no device arrays, the same
discipline as ``sharding_audit``), and the kernels' launch geometry is
plain arithmetic over block shapes.

Three rule families (catalog: docs/static_analysis.md):

========  ========  =====================================================
VN400     warning   unguarded ``log``/``div``/``rsqrt``: the operand's
                    dataflow cone reaches a step input with no
                    positivity guard (eps add, ``maximum`` with a
                    positive constant, ``exp``, squaring...) on the way
VN401     warning   unguarded ``exp``: the operand is not bounded above
                    (no ``minimum``/``clamp``/``x - max(x)`` guard) —
                    overflows to inf for inputs past ~88 (f32)
VN402     warning   ``log(softmax(x))`` instead of ``log_softmax``:
                    the exp->normalize->log round trip underflows to
                    ``log(0) = -inf`` exactly where the model is most
                    confident
VN403     warning   sum/mean accumulation in a <=16-bit dtype over a
                    large reduced axis — bf16 has 8 mantissa bits, the
                    tail of a long sum is rounded away
VN404     warning   integer-narrowing cast whose operand is not
                    provably in range (no clamp) — silent wraparound
VR500     warning   ``jax.random`` key reuse: one key (or two
                    ``fold_in`` derivations with the same counter)
                    consumed by two random draws — the draws correlate
VR501     warning   named prng streams with colliding seeds in the
                    global registry (veles_tpu.prng) — two "independent"
                    streams replay each other
VR502     error     host ``numpy.random`` call in staged code: it runs
                    ONCE at trace time and bakes constants — every step
                    reuses the same "random" numbers
VR503     warning   scatter-add on float outputs with possibly-duplicate
                    indices — accumulation order is unspecified, results
                    differ run to run on parallel backends
VP600     warning   Pallas block shape not aligned to the dtype's native
                    TPU tile (8/16/32 sublanes x 128 lanes) — Mosaic
                    retiles every VMEM copy
VP601     warning   grid axis does not divide its array length and the
                    kernel neither pads nor masks the tail — the last
                    block reads/writes out of bounds or garbage
VP602     error     static per-kernel VMEM footprint (refs double-
                    buffered + accumulators) exceeds the per-core VMEM
                    budget — the kernel will not fit
========  ========  =====================================================

Everything here is static: ``jax.make_jaxpr`` over abstract values for
the VN/VR rules (asserted dispatch-free in tests), registry inspection
for VR501, an AST scan of the step's own source for VR502, and pure
block-geometry arithmetic for VP6xx.
"""

import ast
import inspect
import textwrap

import jax
import numpy as np

from veles_tpu.analysis.findings import ERROR, WARNING, Finding
from veles_tpu.analysis.staging import _sub_jaxprs

#: per-core VMEM budget the VP602 estimate is judged against, KiB
#: (~16 MiB on current TPU generations — pallas guide "Memory Spaces")
DEFAULT_VMEM_KIB = 16 * 1024

#: reduced-element count above which a <=16-bit sum is VN403 (an
#: 8-mantissa-bit bf16 sum starts dropping ulps well before this; 1024
#: keeps small per-tile reductions out of the findings)
LOW_PRECISION_REDUCE_ELEMS = 1024

# ---------------------------------------------------------------------------
# VN4xx: value-range dataflow over the jaxpr
# ---------------------------------------------------------------------------
# Each var carries a small flag set:
#   POS      provably > 0 everywhere
#   NONNEG   provably >= 0
#   UB       bounded above by a finite static value (exp-safe)
#   SOFTMAX  the output of an exp/sum-exp normalization (feeds VN402)
POS, NONNEG, UB, SOFTMAX = "pos", "nonneg", "ub", "softmax"
#: strictly below 1 (and >= 0): ``pow(b, t)`` with literal 0 < b < 1 and
#: t > 0 — so ``1 - b**t`` is provably positive (adam bias correction)
LT1 = "lt1"


def _float_dtype(dt):
    """jnp.issubdtype, not np: bf16/f8 are ml_dtypes extension types
    (numpy kind 'V') that np.issubdtype refuses to call floating."""
    import jax.numpy as jnp
    return jnp.issubdtype(np.dtype(dt), jnp.floating)

#: jax's OWN numerically-stable kernels, recognized by the pjit name
#: their jax.nn/jnp implementations stage under.  Their internals are
#: deliberately stable (softplus' jvp is exp(x - softplus(x)) <= 1,
#: provable only with function-level bounds no flag lattice carries) —
#: the auditor's job is the MODEL's numerics, not re-verifying jax's,
#: so VN400/VN401 skip findings whose innermost named scope is one of
#: these.
_STABLE_IMPL_CTX = frozenset((
    "softplus", "logaddexp", "logaddexp2", "logsumexp", "log_sigmoid",
    "sigmoid", "expit", "log1p", "xlogy", "xlog1py", "entr",
    "log_softmax", "_softmax", "softmax", "erf_inv", "ndtri",
))

#: ops that forward their operand's value range unchanged (the identity
#: chain both the flag propagation and the origin walk see through)
_IDENTITY_PRIMS = frozenset((
    "broadcast_in_dim", "reshape", "transpose", "squeeze", "expand_dims",
    "copy", "stop_gradient", "slice", "rev", "gather", "dynamic_slice",
    "optimization_barrier", "reduce_precision", "sharding_constraint",
))


def _lit_val(v):
    """Scalar value of a Literal / unit-sized constant, else None."""
    val = getattr(v, "val", None)
    if val is None:
        return None
    try:
        arr = np.asarray(val)
    except Exception:  # noqa: BLE001 — opaque const (e.g. a prng key)
        return None
    if arr.size != 1 or not np.issubdtype(arr.dtype, np.number):
        return None
    return float(arr.reshape(()))


def _lit_flags(v):
    x = _lit_val(v)
    if x is None:
        val = getattr(v, "val", None)
        if val is None:
            return frozenset()
        try:
            arr = np.asarray(val)
        except Exception:  # noqa: BLE001
            return frozenset()
        if arr.size == 0 or not np.issubdtype(arr.dtype, np.number):
            return frozenset()
        flags = set()
        if np.isfinite(arr).all():
            flags.add(UB)
            if (arr > 0).all():
                flags.update((POS, NONNEG))
            elif (arr >= 0).all():
                flags.add(NONNEG)
        return frozenset(flags)
    flags = set()
    if np.isfinite(x):
        flags.add(UB)
    if x > 0:
        flags.update((POS, NONNEG))
    elif x == 0:
        flags.add(NONNEG)
    return frozenset(flags)


class _NumericsScan(object):
    """One recursive walk of a closed jaxpr that runs every VN/VR jaxpr
    rule.  Sub-jaxprs under pjit/custom-vjp/remat inherit their caller's
    flags and key classes; scan/while/cond bodies are walked with
    unknown inputs (conservative: their guards are still seen locally,
    their findings still surface)."""

    def __init__(self, name, reduce_elems=LOW_PRECISION_REDUCE_ELEMS):
        self.name = name
        self.reduce_elems = reduce_elems
        self.findings = []
        self._fired = set()          # (rule, detail-key) dedup
        # VR500: key-equivalence classes -> number of consuming draws
        self._key_uses = {}
        self._key_sources = {}       # class -> human description
        self._fold_memo = {}         # (class, counter-token) -> class
        self._next_class = [0]
        # scalar constant folding: var -> float value, for values that
        # are pure literal arithmetic (jnp.var's ``n - ddof``, adam's
        # hyper scalars) — lets the div guard see through them
        self._consts = {}

    # -- bookkeeping --------------------------------------------------------
    def _emit(self, rule, severity, message, hint="", key=None):
        if (rule, key) in self._fired:
            return
        self._fired.add((rule, key))
        self.findings.append(Finding(rule, severity, self.name, message,
                                     hint=hint))

    @staticmethod
    def _is_float(aval):
        dt = getattr(aval, "dtype", None)
        return dt is not None and _float_dtype(dt)

    @staticmethod
    def _is_key(aval):
        dt = getattr(aval, "dtype", None)
        if dt is None:
            return False
        try:
            return jax.dtypes.issubdtype(dt, jax.dtypes.prng_key)
        except Exception:  # noqa: BLE001 — older dtype objects
            return "key" in str(dt)

    def _new_key_class(self, desc):
        self._next_class[0] += 1
        c = self._next_class[0]
        self._key_sources[c] = desc
        return c

    # -- entry point --------------------------------------------------------
    def run(self, closed, input_flags=None):
        flags = {}
        keys = {}
        for v in closed.jaxpr.constvars:
            if self._is_key(v.aval):
                keys[v] = self._new_key_class("a captured key constant")
        for i, v in enumerate(closed.jaxpr.invars):
            if self._is_key(v.aval):
                keys[v] = self._new_key_class("input leaf %d" % i)
            if input_flags and i in input_flags:
                flags[v] = frozenset(input_flags[i])
        self._walk(closed.jaxpr, flags, keys)
        for cls, n in sorted(self._key_uses.items()):
            if n < 2:
                continue
            self._emit(
                "VR500", WARNING,
                "PRNG key reuse: %s feeds %d independent random draws — "
                "the draws are identical/correlated, not independent"
                % (self._key_sources.get(cls, "a key"), n),
                hint="split or fold_in a fresh key per draw "
                     "(jax.random.split / fold_in with distinct "
                     "counters); veles_tpu.prng streams advance a "
                     "counter per draw for exactly this reason",
                key=cls)
        return self.findings

    # -- flag/key lookup helpers -------------------------------------------
    def _get(self, table, v, default=frozenset()):
        if hasattr(v, "val"):        # Literal
            return _lit_flags(v) if table is not None else None
        return table.get(v, default)

    def _kget(self, keys, v):
        if hasattr(v, "val"):
            return None
        return keys.get(v)

    def _cval(self, v):
        """Known scalar value of ``v``: a Literal, or a var the
        constant-folding pass resolved."""
        if hasattr(v, "val"):
            return _lit_val(v)
        return self._consts.get(v)

    #: scalar arithmetic the const-folding pass evaluates (comparisons
    #: fold to 1.0/0.0 so a constant `where` predicate — jnp.var's
    #: ddof-count guard — resolves to its live branch)
    _CONST_OPS = {
        "add": lambda a, b: a + b, "sub": lambda a, b: a - b,
        "mul": lambda a, b: a * b, "max": max, "min": min,
        "div": lambda a, b: (a / b) if b else None,
        "neg": lambda a: -a, "abs": abs,
        "pow": lambda a, b: a ** b if a > 0 else None,
        "gt": lambda a, b: float(a > b), "lt": lambda a, b: float(a < b),
        "ge": lambda a, b: float(a >= b),
        "le": lambda a, b: float(a <= b),
        "eq": lambda a, b: float(a == b),
        "ne": lambda a, b: float(a != b),
    }

    def _fold_const(self, eqn):
        """Record (and return) the outvar's value when every operand is
        a known scalar — pure literal arithmetic only."""
        prim = eqn.primitive.name
        if prim in ("convert_element_type", "broadcast_in_dim",
                    "reshape", "squeeze", "copy", "stop_gradient"):
            cv = self._cval(eqn.invars[0])
        elif prim in self._CONST_OPS:
            vals = [self._cval(v) for v in eqn.invars]
            if any(x is None for x in vals):
                return None
            try:
                cv = self._CONST_OPS[prim](*vals)
            except Exception:  # noqa: BLE001 — overflow etc.
                return None
        else:
            return None
        if cv is not None:
            for ov in eqn.outvars:
                self._consts[ov] = cv
        return cv

    @staticmethod
    def _val_flags(x):
        flags = set()
        if np.isfinite(x):
            flags.add(UB)
        if x > 0:
            flags.update((POS, NONNEG))
        elif x == 0:
            flags.add(NONNEG)
        return frozenset(flags)

    # -- the walk -----------------------------------------------------------
    def _walk(self, jaxpr, flags, keys, ctx=""):
        defs = {}
        for eqn in jaxpr.eqns:
            for ov in eqn.outvars:
                defs[ov] = eqn
        for eqn in jaxpr.eqns:
            self._visit(eqn, flags, keys, defs, ctx)

    def _origin(self, v, defs):
        """Walk back through value-preserving ops (and ``max`` with a
        literal) to the semantic source var — lets the ``exp(x - max(x))``
        pattern match through broadcast/stop_gradient glue."""
        seen = 0
        while seen < 64:
            seen += 1
            if hasattr(v, "val"):    # Literal: its own origin
                return v
            eqn = defs.get(v)
            if eqn is None:
                return v
            prim = eqn.primitive.name
            if prim in _IDENTITY_PRIMS or prim == "convert_element_type":
                v = eqn.invars[0]
                continue
            if prim == "max":
                non_lit = [iv for iv in eqn.invars
                           if not hasattr(iv, "val")]
                if len(non_lit) == 1:
                    v = non_lit[0]
                    continue
            return v
        return v

    def _chain_prim(self, v, defs, prim_names, depth=8):
        """The defining eqn of ``v``, looking through identity glue, if
        its primitive is in ``prim_names``."""
        for _ in range(depth):
            if hasattr(v, "val"):
                return None
            eqn = defs.get(v)
            if eqn is None:
                return None
            prim = eqn.primitive.name
            if prim in prim_names:
                return eqn
            if prim in _IDENTITY_PRIMS or prim == "convert_element_type":
                v = eqn.invars[0]
                continue
            if prim == "max":
                # ``max(-inf, reduce_max(x))`` — the empty-reduction
                # guard every jax softmax lowering inserts
                non_lit = [iv for iv in eqn.invars
                           if not hasattr(iv, "val")]
                if len(non_lit) == 1:
                    v = non_lit[0]
                    continue
            return None
        return None

    def _visit(self, eqn, flags, keys, defs, ctx=""):
        prim = eqn.primitive.name
        get = lambda v: self._get(flags, v)  # noqa: E731

        # ---- recurse into sub-jaxprs -----------------------------------
        if prim in ("pjit", "closed_call", "core_call", "remat",
                    "remat2", "checkpoint", "custom_jvp_call",
                    "custom_vjp_call", "custom_vjp_call_jaxpr",
                    "custom_jvp_call_jaxpr"):
            sub = None
            for pname in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                cj = eqn.params.get(pname)
                if cj is not None:
                    sub = getattr(cj, "jaxpr", cj)
                    break
            if sub is not None and hasattr(sub, "eqns"):
                in_flags, in_keys = {}, {}
                n = min(len(sub.invars), len(eqn.invars))
                for iv, ov in zip(eqn.invars[-n:] if len(eqn.invars) > n
                                  else eqn.invars, sub.invars):
                    in_flags[ov] = get(iv)
                    cv = self._cval(iv)
                    if cv is not None:
                        self._consts[ov] = cv
                    kc = self._kget(keys, iv)
                    if kc is not None:
                        in_keys[ov] = kc
                    elif self._is_key(ov.aval):
                        in_keys[ov] = self._new_key_class(
                            "a key entering %s" % prim)
                # unnamed call wrappers (custom_jvp_call, remat) keep
                # the enclosing scope's name — softplus's jvp body must
                # still read as softplus
                self._walk(sub, in_flags, in_keys,
                           ctx=str(eqn.params.get("name") or ctx))
                for ov, sv in zip(eqn.outvars, sub.outvars):
                    flags[ov] = self._get(in_flags, sv)
                    cv = self._cval(sv)
                    if cv is not None:
                        self._consts[ov] = cv
                    if self._is_key(ov.aval):
                        kc = self._kget(in_keys, sv)
                        keys[ov] = (kc if kc is not None
                                    else self._new_key_class(
                                        "a key from %s" % prim))
                return
            # unknown call structure: fall through to generic handling

        if prim == "scan":
            # consts and per-iteration xs slices keep their caller
            # flags; the CARRY enters unknown (a sound fixpoint skip:
            # body-derived flags then hold for any carry).  Body outvar
            # flags map back out — stacked ys flags hold elementwise,
            # so a `maximum(l, eps)` residual stays provably positive
            # into the backward scan (the online-softmax guard).
            cj = eqn.params.get("jaxpr")
            sub = getattr(cj, "jaxpr", cj)
            if sub is not None and hasattr(sub, "eqns"):
                nc = int(eqn.params.get("num_consts", 0))
                ncar = int(eqn.params.get("num_carry", 0))
                in_flags, in_keys = {}, {}
                for i, (iv, ov) in enumerate(zip(eqn.invars,
                                                 sub.invars)):
                    carry = nc <= i < nc + ncar
                    in_flags[ov] = frozenset() if carry else get(iv)
                    if not carry:
                        cv = self._cval(iv)
                        if cv is not None:
                            self._consts[ov] = cv
                    if self._is_key(ov.aval):
                        kc = None if carry else self._kget(keys, iv)
                        in_keys[ov] = (kc if kc is not None else
                                       self._new_key_class(
                                           "a key entering scan"))
                self._walk(sub, in_flags, in_keys, ctx=ctx)
                for ov, sv in zip(eqn.outvars, sub.outvars):
                    flags[ov] = self._get(in_flags, sv)
            else:
                for value in eqn.params.values():
                    for s in _sub_jaxprs(value):
                        self._walk(s, {}, {}, ctx=ctx)
            for ov in eqn.outvars:
                if self._is_key(ov.aval):
                    keys[ov] = self._new_key_class("a key from scan")
            return
        if prim == "cond":
            # each branch binds the operands (eqn.invars[1:]) directly
            # — caller flags hold inside; outputs take the intersection
            # over branches (grad-accum wraps the whole optimizer
            # update in a cond, and adam's step-counter vouching must
            # survive it)
            branches = eqn.params.get("branches", ())
            out_sets = None
            for br in branches:
                sub = getattr(br, "jaxpr", br)
                if not hasattr(sub, "eqns"):
                    continue
                in_flags, in_keys = {}, {}
                for iv, ov in zip(eqn.invars[1:], sub.invars):
                    in_flags[ov] = get(iv)
                    cv = self._cval(iv)
                    if cv is not None:
                        self._consts[ov] = cv
                    kc = self._kget(keys, iv)
                    if kc is not None:
                        in_keys[ov] = kc
                    elif self._is_key(ov.aval):
                        in_keys[ov] = self._new_key_class(
                            "a key entering cond")
                self._walk(sub, in_flags, in_keys, ctx=ctx)
                brf = [set(self._get(in_flags, sv))
                       for sv in sub.outvars]
                out_sets = (brf if out_sets is None else
                            [a & b for a, b in zip(out_sets, brf)])
            for i, ov in enumerate(eqn.outvars):
                if out_sets is not None and i < len(out_sets):
                    flags[ov] = frozenset(out_sets[i] - {SOFTMAX})
                if self._is_key(ov.aval):
                    keys[ov] = self._new_key_class("a key from cond")
            return
        if prim == "while":
            # the carry loops — bodies run with unknown inputs (guards
            # inside them are still local, hazards still surface)
            for value in eqn.params.values():
                for sub in _sub_jaxprs(value):
                    self._walk(sub, {}, {}, ctx=ctx)
            for ov in eqn.outvars:
                if self._is_key(ov.aval):
                    keys[ov] = self._new_key_class("a key from while")
            return

        # ---- scalar constant folding (jnp.var's n - ddof, adam betas)
        if self._fold_const(eqn) is not None:
            vf = self._val_flags(self._cval(eqn.outvars[0]))
            for ov in eqn.outvars:
                flags[ov] = vf
            return

        # ---- VR5xx: key derivation and consumption ---------------------
        if prim == "random_fold_in":
            src = self._kget(keys, eqn.invars[0])
            if src is None:
                src = self._new_key_class("an untracked key")
                if not hasattr(eqn.invars[0], "val"):
                    keys[eqn.invars[0]] = src
            counter = eqn.invars[1]
            tok = (_lit_val(counter) if hasattr(counter, "val")
                   else id(counter))
            cls = self._fold_memo.get((src, tok))
            if cls is None:
                cls = self._new_key_class(
                    "fold_in(%s, %s)" % (self._key_sources.get(src, "?"),
                                         tok if hasattr(counter, "val")
                                         else "<traced>"))
                self._fold_memo[(src, tok)] = cls
            keys[eqn.outvars[0]] = cls
            return
        if prim in ("random_seed", "random_split"):
            for ov in eqn.outvars:
                keys[ov] = self._new_key_class(prim)
            return
        if prim == "random_wrap":
            kc = self._kget(keys, eqn.invars[0])
            keys[eqn.outvars[0]] = (kc if kc is not None
                                    else self._new_key_class("random_wrap"))
            return
        if prim in ("random_bits", "threefry2x32"):
            kc = self._kget(keys, eqn.invars[0])
            if kc is None and not hasattr(eqn.invars[0], "val"):
                kc = keys.setdefault(eqn.invars[0],
                                     self._new_key_class("a raw key"))
            if kc is not None:
                self._key_uses[kc] = self._key_uses.get(kc, 0) + 1
            return
        if self._is_key(getattr(eqn.outvars[0], "aval", None)) \
                and prim in _IDENTITY_PRIMS:
            # slice/squeeze of a split-key array: each distinct slice is
            # a distinct subkey — key by the slice geometry
            src = self._kget(keys, eqn.invars[0])
            if src is not None:
                geo = (prim,
                       str(eqn.params.get("start_indices", "")),
                       str(eqn.params.get("limit_indices", "")))
                cls = self._fold_memo.get((src, geo))
                if cls is None:
                    cls = (src if prim not in ("slice", "dynamic_slice")
                           else self._new_key_class("a split subkey"))
                    self._fold_memo[(src, geo)] = cls
                keys[eqn.outvars[0]] = cls
            return

        # ---- VR503: scatter-add on floats ------------------------------
        if prim in ("scatter-add", "scatter_add"):
            out_aval = eqn.outvars[0].aval
            dn = eqn.params.get("dimension_numbers")
            unique = bool(eqn.params.get("unique_indices", False))
            batched = bool(getattr(dn, "operand_batching_dims", ()))
            # the transpose of jnp.take (ctx "_take") is the embedding-
            # table gradient: XLA-generated, sequential (deterministic)
            # on TPU, and unavoidable — only handwritten accumulating
            # scatters are actionable
            take_bwd = ctx in ("_take", "take", "take_along_axis")
            if self._is_float(out_aval) and not unique and not batched \
                    and not take_bwd:
                self._emit(
                    "VR503", WARNING,
                    "scatter-add accumulates %s values at "
                    "possibly-duplicate indices — float addition is not "
                    "associative, so the result depends on reduction "
                    "order (nondeterministic on parallel backends)"
                    % out_aval.dtype,
                    hint="sort/segment the indices (jax.ops.segment_sum "
                         "with sorted ids), accumulate in a wider dtype, "
                         "or mark .at[].add(..., unique_indices=True) "
                         "when duplicates are impossible",
                    key=("scatter", str(out_aval.dtype)))
            return

        # ---- VN400/401/402: guarded-transcendental checks --------------
        if prim == "log":
            x = eqn.invars[0]
            fx = get(x)
            softmax_src = SOFTMAX in fx or self._is_softmax_chain(x, defs)
            if softmax_src:
                self._emit(
                    "VN402", WARNING,
                    "log(softmax(x)): the exp-normalize-log round trip "
                    "underflows to log(0) = -inf exactly where the model "
                    "is most confident",
                    hint="use jax.nn.log_softmax (computes x - "
                         "logsumexp(x) directly)",
                    key="log_softmax")
            elif POS not in fx and ctx not in _STABLE_IMPL_CTX:
                self._emit(
                    "VN400", WARNING,
                    "log of a value not provably positive "
                    "(operand %s) — log(0) = -inf, log(<0) = nan"
                    % _short_aval(x),
                    hint="clamp first (jnp.log(jnp.maximum(x, eps))) or "
                         "restructure so positivity is guaranteed "
                         "(exp, squaring, eps add)",
                    key=("log", id(eqn)))
            flags[eqn.outvars[0]] = frozenset(
                {UB} if UB in fx else ())
            return
        if prim == "rsqrt":
            fx = get(eqn.invars[0])
            if POS not in fx and ctx not in _STABLE_IMPL_CTX:
                self._emit(
                    "VN400", WARNING,
                    "rsqrt of a value not provably positive "
                    "(operand %s) — rsqrt(0) = inf, rsqrt(<0) = nan"
                    % _short_aval(eqn.invars[0]),
                    hint="add an eps before the rsqrt "
                         "(jax.lax.rsqrt(x + 1e-6)), the layer-norm "
                         "idiom",
                    key=("rsqrt", id(eqn)))
            flags[eqn.outvars[0]] = frozenset((POS, NONNEG)) \
                if POS in fx else frozenset((NONNEG,))
            return
        if prim == "div":
            num, den = eqn.invars
            fden = get(den)
            cv = self._cval(den)
            if self._is_float(eqn.outvars[0].aval) and POS not in fden \
                    and not (cv is not None and cv != 0.0) \
                    and ctx not in _STABLE_IMPL_CTX:
                self._emit(
                    "VN400", WARNING,
                    "division by a value not provably nonzero "
                    "(denominator %s) — x/0 = inf/nan propagates "
                    "through the whole step" % _short_aval(den),
                    hint="guard the denominator "
                         "(jnp.maximum(d, 1) for counts, + eps for "
                         "norms) — the loss already divides by "
                         "maximum(n_valid, 1)",
                    key=("div", id(eqn)))
            fnum = get(num)
            out = set()
            if POS in fnum and POS in fden:
                out.update((POS, NONNEG))
            elif NONNEG in fnum and POS in fden:
                out.add(NONNEG)
            # exp(x)/sum(exp(x)) — the softmax shape: in (0, 1], so
            # also bounded above (a softmax OUTPUT layer feeding the
            # loss keeps downstream exps guarded)
            if self._softmax_div(num, den, defs):
                out.update((SOFTMAX, UB, POS, NONNEG))
            flags[eqn.outvars[0]] = frozenset(out)
            return
        if prim == "exp":
            x = eqn.invars[0]
            fx = get(x)
            if UB not in fx and not self._sub_max_guard(x, defs) \
                    and ctx not in _STABLE_IMPL_CTX:
                self._emit(
                    "VN401", WARNING,
                    "exp of a value not bounded above "
                    "(operand %s) — overflows to inf past ~88 (f32) / "
                    "~11 (bf16 range is wide but the sum that usually "
                    "follows is not)" % _short_aval(x),
                    hint="subtract the running max first (the "
                         "online-softmax identity exp(x - max(x))), or "
                         "clamp the exponent",
                    key=("exp", id(eqn)))
            flags[eqn.outvars[0]] = frozenset(
                {POS, NONNEG} | ({UB} if UB in fx else set()))
            return

        # ---- VN403: low-precision accumulation -------------------------
        if prim == "dot_general":
            out_aval = eqn.outvars[0].aval
            dt = getattr(out_aval, "dtype", None)
            if dt is not None and _float_dtype(dt) \
                    and np.dtype(dt).itemsize <= 2:
                dn = eqn.params.get("dimension_numbers")
                ((lhs_c, _rhs_c), _batch) = dn
                shape = getattr(eqn.invars[0].aval, "shape", ())
                k = 1
                for a in lhs_c:
                    k *= shape[a] if a < len(shape) else 1
                if k >= self.reduce_elems:
                    self._emit(
                        "VN403", WARNING,
                        "dot_general contracts %d elements with a %s "
                        "accumulator — the MXU accumulates f32 only "
                        "when preferred_element_type says so; a <=16-"
                        "bit output dtype rounds the running sum"
                        % (k, dt),
                        hint="pass preferred_element_type=jnp.float32 "
                             "(ops/linear.py pins policy.accum) and "
                             "cast down after the reduction",
                        key=("dot", str(dt), k))
            flags[eqn.outvars[0]] = frozenset()
            return
        if prim == "reduce_sum":
            x = eqn.invars[0]
            aval = getattr(x, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is not None and _float_dtype(dt) \
                    and np.dtype(dt).itemsize <= 2:
                shape = getattr(aval, "shape", ())
                axes = eqn.params.get("axes", ())
                n = 1
                for a in axes:
                    n *= shape[a] if a < len(shape) else 1
                if n >= self.reduce_elems:
                    self._emit(
                        "VN403", WARNING,
                        "sum over %d elements accumulates in %s — with "
                        "<= 11 mantissa bits the tail of a long sum is "
                        "rounded away (loss/metric drift)" % (n, dt),
                        hint="accumulate in f32: x.astype(jnp.float32)"
                             ".sum() (every loss in ops/losses.py "
                             "does), or keep dot accumulation in f32 "
                             "via preferred_element_type",
                        key=("reduce", str(dt), n))
            f = set(get(x) & {POS, NONNEG})
            # the max-gradient tie count — sum(x == max(x)) — is >= 1
            # by construction (the max is attained); its div shows up
            # in the VJP of every jnp.max / reduce-max
            eq = self._chain_prim(x, defs, ("eq",))
            if eq is not None:
                a, b = eq.invars[:2]
                if self._reduce_max_of(b, a, defs) \
                        or self._reduce_max_of(a, b, defs):
                    f.update((POS, NONNEG))
            flags[eqn.outvars[0]] = frozenset(f)
            return

        # ---- VN404: integer-narrowing casts ----------------------------
        if prim == "convert_element_type":
            x = eqn.invars[0]
            src_dt = np.dtype(getattr(getattr(x, "aval", None), "dtype",
                                      np.float32))
            dst_dt = np.dtype(eqn.params.get("new_dtype", np.float32))
            fx = get(x)
            if np.issubdtype(src_dt, np.integer) \
                    and np.issubdtype(dst_dt, np.integer) \
                    and dst_dt.itemsize < src_dt.itemsize \
                    and not (UB in fx and NONNEG in fx) \
                    and not self._clamped_to_range(x, dst_dt, defs):
                self._emit(
                    "VN404", WARNING,
                    "narrowing integer cast %s -> %s of an unbounded "
                    "value — out-of-range values wrap around silently"
                    % (src_dt, dst_dt),
                    hint="jnp.clip to the target range before the cast",
                    key=("cast", str(src_dt), str(dst_dt)))
            # float->float and widening casts preserve range flags
            flags[eqn.outvars[0]] = fx
            return

        # ---- generic flag propagation ----------------------------------
        flags_out = self._propagate(prim, eqn, get, defs)
        for ov in eqn.outvars:
            flags[ov] = flags_out

    # -- propagation / pattern helpers --------------------------------------
    def _propagate(self, prim, eqn, get, defs):
        ins = [get(v) for v in eqn.invars]
        if prim in _IDENTITY_PRIMS:
            return ins[0] if ins else frozenset()
        if prim == "add" or prim == "add_any":
            out = set()
            if len(ins) == 2:
                a, b = ins
                if (POS in a and NONNEG in b) or (NONNEG in a
                                                  and POS in b):
                    out.update((POS, NONNEG))
                elif NONNEG in a and NONNEG in b:
                    out.add(NONNEG)
                if UB in a and UB in b:
                    out.add(UB)
            return frozenset(out)
        if prim == "sub":
            out = set()
            a, b = ins
            # a - b is bounded above only when a is AND b is bounded
            # below (c - x overflows exp for very negative x)
            if UB in a and (NONNEG in b or POS in b):
                out.add(UB)
            elif self._reduce_max_of(eqn.invars[1], eqn.invars[0], defs):
                out.add(UB)          # x - max(x) <= 0
            # 1 - b**t (adam bias correction): literal >= 1 minus a
            # value provably in [0, 1) is positive
            lit = _lit_val(eqn.invars[0])
            if lit is not None and lit >= 1.0 and LT1 in b:
                out.update((POS, NONNEG, UB))
            return frozenset(out)
        if prim == "mul":
            out = set()
            a, b = ins
            same = (self._origin(eqn.invars[0], defs)
                    is self._origin(eqn.invars[1], defs))
            if same:
                out.add(NONNEG)      # x * x
                if POS in a:
                    out.add(POS)
            elif POS in a and POS in b:
                out.update((POS, NONNEG))
            elif NONNEG in a and NONNEG in b:
                out.add(NONNEG)
            if UB in a and UB in b and NONNEG in a and NONNEG in b:
                out.add(UB)
            return frozenset(out)
        if prim == "max":
            a, b = ins
            out = set()
            if POS in a or POS in b:
                out.update((POS, NONNEG))
            elif NONNEG in a or NONNEG in b:
                out.add(NONNEG)
            if UB in a and UB in b:
                out.add(UB)
            return frozenset(out)
        if prim == "min":
            a, b = ins
            out = set()
            if POS in a and POS in b:
                out.update((POS, NONNEG))
            elif NONNEG in a and NONNEG in b:
                out.add(NONNEG)
            if UB in a or UB in b:
                out.add(UB)
            return frozenset(out)
        if prim == "clamp":
            lo, _x, hi = ins
            out = set()
            if POS in lo:
                out.update((POS, NONNEG))
            elif NONNEG in lo:
                out.add(NONNEG)
            if UB in hi:
                out.add(UB)
            return frozenset(out)
        if prim in ("abs", "square"):
            return frozenset((NONNEG,))
        if prim == "neg":
            a = ins[0]
            return frozenset({UB} if NONNEG in a else set())
        if prim == "sqrt":
            a = ins[0]
            out = {NONNEG}
            if POS in a:
                out.add(POS)
            if UB in a:
                out.add(UB)
            return frozenset(out)
        if prim == "integer_pow":
            y = eqn.params.get("y", 1)
            if isinstance(y, int) and y % 2 == 0 and y > 0:
                return frozenset((NONNEG,))
            return ins[0] if y == 1 else frozenset()
        if prim == "pow":
            a = ins[0]
            base = _lit_val(eqn.invars[0])
            if base is not None and 0.0 < base < 1.0 \
                    and POS in ins[1]:
                return frozenset((POS, NONNEG, UB, LT1))
            if POS in a:
                return frozenset((POS, NONNEG))
            return frozenset()
        if prim == "logistic":
            return frozenset((NONNEG, UB))
        if prim == "erf":
            return frozenset((UB,))      # erf ranges over [-1, 1]
        if prim in ("tanh", "sin", "cos", "erf_inv"):
            return frozenset({UB} if prim in ("tanh", "sin", "cos")
                             else set())
        if prim == "log1p":
            return frozenset(set(ins[0]) & {POS, NONNEG, UB})
        if prim == "exp2":
            return frozenset(
                {POS, NONNEG} | ({UB} if UB in ins[0] else set()))
        if prim == "reduce_window_sum":
            f = set(ins[0]) & {POS, NONNEG}
            # avg-pool count normalization: the window sum of (padded)
            # ones — every pooling window overlaps >= 1 real element by
            # construction, so the count is >= 1
            if POS not in f and self._ones_window(eqn.invars[0], defs):
                f.update((POS, NONNEG))
            return frozenset(f)
        if prim == "reduce_max":
            f = ins[0]
            return frozenset(f & {POS, NONNEG, UB})
        if prim == "reduce_min":
            f = ins[0]
            return frozenset(f & {POS, NONNEG, UB})
        if prim == "reduce_prod":
            f = ins[0]
            return frozenset(f & {POS, NONNEG})
        if prim == "select_n":
            cases = ins[1:]
            if not cases:
                return frozenset()
            pred = self._cval(eqn.invars[0])
            if pred is not None:      # constant predicate: live branch
                i = min(int(pred), len(cases) - 1)
                return frozenset(set(cases[i]) - {SOFTMAX})
            out = set(cases[0])
            for c in cases[1:]:
                out &= set(c)
            out.discard(SOFTMAX)
            # jnp.where(mask, softmax_p, 0) keeps the softmax shape
            if all(SOFTMAX in c or self._zero_literal(v)
                   for c, v in zip(cases, eqn.invars[1:])) \
                    and any(SOFTMAX in c for c in cases):
                out.add(SOFTMAX)
            return frozenset(out)
        if prim == "iota":
            return frozenset((NONNEG, UB))
        if prim == "concatenate":
            out = set(ins[0]) if ins else set()
            for f in ins[1:]:
                out &= set(f)
            return frozenset(out)
        if prim == "dot_general":
            return frozenset()
        if prim == "pad":
            a = ins[0]
            pv = ins[1] if len(ins) > 1 else frozenset()
            return frozenset(set(a) & set(pv) & {POS, NONNEG, UB})
        return frozenset()

    @staticmethod
    def _zero_literal(v):
        return _lit_val(v) == 0.0

    def _clamped_to_range(self, v, dst_dt, defs):
        """``v`` is (glue around) a ``clamp``/``max``+``min`` whose
        literal bounds fit the target integer dtype — the documented
        VN404 fix ``jnp.clip(x, -128, 127).astype(jnp.int8)`` must
        pass for SIGNED ranges too (the flag lattice has no
        bounded-below fact)."""
        lo = hi = None
        eqn = self._chain_prim(v, defs, ("clamp", "pjit"))
        if eqn is None:
            return False
        if eqn.primitive.name == "clamp":
            lo = self._cval(eqn.invars[0])
            hi = self._cval(eqn.invars[2])
        elif eqn.params.get("name") == "clip" \
                and len(eqn.invars) >= 3:
            # jnp.clip stages as pjit[name=clip](x, lo, hi)
            lo = self._cval(eqn.invars[1])
            hi = self._cval(eqn.invars[2])
        if lo is None or hi is None:
            return False
        info = np.iinfo(dst_dt)
        return info.min <= lo and hi <= info.max

    def _ones_window(self, v, defs, depth=8):
        """``v`` is (identity/zero-pad glue around) a broadcast of a
        positive literal — the avg-pool per-position window count."""
        for _ in range(depth):
            if hasattr(v, "val"):
                x = _lit_val(v)
                return x is not None and x > 0
            eqn = defs.get(v)
            if eqn is None:
                return False
            prim = eqn.primitive.name
            if prim in _IDENTITY_PRIMS or prim == "convert_element_type":
                v = eqn.invars[0]
                continue
            if prim == "pad":
                v = eqn.invars[0]
                continue
            return False
        return False

    def _reduce_max_of(self, b, a, defs, depth=10):
        """True when ``b`` provably dominates ``a`` elementwise-or-
        broadcast — i.e. ``a - b <= 0``, the online-softmax bound.
        Two shapes, searched through identity glue and through BOTH
        operands of ``max`` (max only raises a bound):

        * ``b`` reaches ``reduce_max`` of ``a``'s origin
          (``exp(x - max(x))``, jax's log_softmax lowering);
        * ``b`` reaches ``a``'s origin itself
          (``exp(m_prev - max(m_prev, ...))``, the running-max
          correction in every online-softmax / flash kernel body)."""
        target = self._origin(a, defs)
        stack, seen = [(b, depth)], set()
        while stack:
            v, d = stack.pop()
            if d <= 0 or hasattr(v, "val"):
                continue
            if v in seen:
                continue
            seen.add(v)
            if self._origin(v, defs) is target:
                return True
            eqn = defs.get(v)
            if eqn is None:
                continue
            prim = eqn.primitive.name
            if prim == "reduce_max":
                if self._origin(eqn.invars[0], defs) is target:
                    return True
                continue
            if prim in _IDENTITY_PRIMS or prim == "convert_element_type":
                stack.append((eqn.invars[0], d - 1))
            elif prim == "max":
                for iv in eqn.invars:
                    stack.append((iv, d - 1))
        return False

    def _sub_max_guard(self, x, defs):
        eqn = self._chain_prim(x, defs, ("sub",))
        if eqn is None:
            return False
        return self._reduce_max_of(eqn.invars[1], eqn.invars[0], defs)

    def _softmax_div(self, num, den, defs):
        """exp(u) / [broadcast of] reduce_sum(exp(u)) — raw softmax."""
        num_exp = self._chain_prim(num, defs, ("exp",))
        if num_exp is None:
            return False
        den_sum = self._chain_prim(den, defs, ("reduce_sum",))
        if den_sum is None:
            return False
        den_exp = self._chain_prim(den_sum.invars[0], defs, ("exp",))
        return den_exp is not None

    def _is_softmax_chain(self, v, defs):
        eqn = self._chain_prim(v, defs, ("div",))
        if eqn is None:
            return False
        return self._softmax_div(eqn.invars[0], eqn.invars[1], defs)


def _short_aval(v):
    aval = getattr(v, "aval", None)
    return "%s[%s]" % (getattr(aval, "dtype", "?"),
                       ",".join(map(str, getattr(aval, "shape", ()))))


# ---------------------------------------------------------------------------
# VR502: host numpy.random in staged source
# ---------------------------------------------------------------------------
def _np_random_calls(fn):
    """Attribute chains ``np.random...`` / ``numpy.random...`` in the
    source of ``fn`` (and any lambdas/inner defs it contains).  Host
    randomness inside a staged step runs once at trace time and bakes
    the SAME values into every iteration."""
    fn = inspect.unwrap(getattr(fn, "__wrapped__", fn))
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return []
    hits = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Attribute):
            continue
        base = node.value
        if isinstance(base, ast.Name) and base.id in ("np", "numpy") \
                and node.attr == "random":
            hits.append("%s.random (line %d)" % (base.id,
                                                 node.lineno))
    return hits


# ---------------------------------------------------------------------------
# public entry points
# ---------------------------------------------------------------------------
def audit_numerics_step(spec):
    """VN4xx/VR5xx audit of one staged step.

    ``spec`` (the shape ``StagedTrainer.lint_numerics_spec()`` returns):

    ``fn``        the step — jitted object or plain callable
    ``args``      positional args: concrete arrays and/or
                  ``jax.ShapeDtypeStruct`` specs (never executed)
    ``name``      display name for findings
    ``suppress``  optional iterable of rule ids to drop (the explicit
                  "checked" escape hatch — e.g. a loss registered with
                  ``register_loss(..., numerics_suppress=("VN403",))``)
    ``reduce_elems``  optional VN403 threshold override
    ``input_flags``   optional {flat-input-leaf-index: flag names} the
                  caller can VOUCH for — e.g. the trainer pins its step
                  counter positive (it increments before dispatch), so
                  adam's ``1 - beta**t`` bias correction proves out
    ``host_scan``     optional extra callables whose SOURCE joins the
                  VR502 host-randomness scan — the trainer passes its
                  loss evaluator and any user-defined (non-veles_tpu)
                  layers, since the staged step fn itself is framework
                  code and the user's host calls live in its callees

    Tracing is abstract (``jax.make_jaxpr``): no device arrays, no
    dispatch — asserted in tests/test_numerics_audit.py."""
    name = spec.get("name", "step")
    fn = spec["fn"]
    suppress = frozenset(spec.get("suppress", ()))

    findings = []
    seen_hits = set()
    for scanned in (fn,) + tuple(spec.get("host_scan", ())):
        for hit in _np_random_calls(scanned):
            where = getattr(scanned, "__name__", "step")
            if (where, hit) in seen_hits:
                continue
            seen_hits.add((where, hit))
            findings.append(Finding(
                "VR502", ERROR, name,
                "host numpy.random call in staged code (%s, in %s): "
                "it runs ONCE at trace time — every step replays the "
                "same \"random\" values" % (hit, where),
                hint="use jax.random with a per-step key (fold_in on "
                     "the step counter), or draw on the host OUTSIDE "
                     "the step via veles_tpu.prng streams"))

    try:
        closed = jax.make_jaxpr(fn)(*spec.get("args", ()))
    except Exception as e:  # noqa: BLE001 — trace failure is VJ100's job
        findings.append(Finding(
            "VJ100", ERROR, name,
            "staged step failed to trace abstractly for the numerics "
            "audit: %s: %s" % (type(e).__name__, e),
            hint="the step must trace over abstract inputs — no "
                 "data-dependent python control flow"))
        return findings

    scan = _NumericsScan(
        name, reduce_elems=int(spec.get("reduce_elems",
                                        LOW_PRECISION_REDUCE_ELEMS)))
    findings.extend(scan.run(closed,
                             input_flags=spec.get("input_flags")))
    if suppress:
        findings = [f for f in findings if f.rule not in suppress]
    return findings


def audit_prng_registry(name="<prng>"):
    """VR501: named streams in the global ``veles_tpu.prng`` registry
    whose effective seeds collide — their entire futures replay each
    other.  Derived (hash-offset) seeds are rehashed away at creation
    (prng.py); what remains is explicit seeding."""
    from veles_tpu import prng
    findings = []
    for names, seed in prng.seed_collisions():
        findings.append(Finding(
            "VR501", WARNING, name,
            "prng streams %s share seed %d — every draw in one replays "
            "the other (fold_in counters advance in lockstep)"
            % (", ".join(sorted(names)), seed),
            hint="seed streams differently (prng.get(name).seed(s)), or "
                 "let the per-name sha1 offset derive them from "
                 "root.common.random_seed"))
    return findings


# ---------------------------------------------------------------------------
# VP6xx: Pallas kernel launch geometry
# ---------------------------------------------------------------------------
def _sublane_tile(dtype):
    """Native TPU sublane tile for a dtype: (8, 128) f32, (16, 128)
    bf16/f16, (32, 128) int8/fp8 — single source of truth shared with
    the paged-serving fallback (ops.pallas.mosaic_sublane_min)."""
    from veles_tpu.ops import pallas as _pallas
    return _pallas.mosaic_sublane_min(dtype)


def audit_kernel_launch(launch, vmem_kib=None):
    """VP6xx findings for one kernel-launch description.

    ``launch`` is the dict shape ``ops.pallas`` audit hooks return:

    ``kernel``    display name, e.g. ``"flash.forward"``
    ``blocks``    [(ref_name, block_shape, dtype), ...] — every VMEM
                  ref the kernel sees (in/out block tiles)
    ``scratch``   [(name, shape, dtype), ...] — VMEM scratch allocations
    ``grid_axes`` [(axis_name, length, block), ...] — launch axes whose
                  length/block divisibility matters
    ``masked``    True when the kernel masks/pads ragged tails (the
                  VP601 escape hatch — our kernels do, docstrings say
                  so, and the tests pin it)
    ``checked``   optional iterable of rule ids deliberately accepted
                  for this launch (escape hatch, mirrors ``suppress``)
    """
    name = launch.get("kernel", "<kernel>")
    checked = frozenset(launch.get("checked", ()))
    budget = int((vmem_kib or launch.get("vmem_kib")
                  or DEFAULT_VMEM_KIB) * 1024)
    findings = []

    for entry in launch.get("blocks", ()):
        ref_name, shape, dtype = entry[:3]
        opts = entry[3] if len(entry) > 3 else {}
        shape = tuple(int(s) for s in shape if int(s) != 1)
        if len(shape) < 2:
            continue
        sub, lane = shape[-2], shape[-1]
        want_sub = _sublane_tile(dtype)
        bad = []
        # a block dim that spans the WHOLE array in that axis is the
        # model's geometry, not a tunable tile choice — e.g. flash's
        # lane dim IS the head dim, and d=64 models exist (the kernel
        # handles the half-tile; only chosen block sizes are lintable)
        if lane % 128 and not opts.get("full_lane"):
            bad.append("lane dim %d %% 128 != 0" % lane)
        if sub % want_sub and not opts.get("full_sublane"):
            bad.append("sublane dim %d %% %d != 0 (%s tile)"
                       % (sub, want_sub, np.dtype(dtype).name))
        if bad and "VP600" not in checked:
            findings.append(Finding(
                "VP600", WARNING, name,
                "block %r %r is not aligned to the %s native tile "
                "(%d, 128): %s — Mosaic retiles every HBM<->VMEM copy"
                % (ref_name, shape, np.dtype(dtype).name, want_sub,
                   "; ".join(bad)),
                hint="round the block dims to multiples of (%d, 128) "
                     "and mask the tail inside the kernel" % want_sub))

    if not launch.get("masked", False) and "VP601" not in checked:
        for axis, length, block in launch.get("grid_axes", ()):
            block = int(block)
            if block and int(length) % block:
                findings.append(Finding(
                    "VP601", WARNING, name,
                    "grid axis %r: length %d is not divisible by block "
                    "%d and the kernel does not mask the ragged tail — "
                    "the last block reads/writes out of range"
                    % (axis, length, block),
                    hint="pad the operand to a block multiple and mask "
                         "inside the kernel (ops/pallas/flash.py's "
                         "_pad_to + validity-mask pattern)"))

    def _bytes(entries):
        total = 0
        for entry in entries:
            _n, shape, dtype = entry[:3]
            n = 1
            for s in shape:
                n *= int(s)
            total += n * np.dtype(dtype).itemsize
        return total

    ref_bytes = _bytes(launch.get("blocks", ()))
    scratch_bytes = _bytes(launch.get("scratch", ()))
    # Mosaic double-buffers the in/out refs so the next grid step's DMA
    # overlaps compute; scratch persists single-buffered
    total = 2 * ref_bytes + scratch_bytes
    if total > budget and "VP602" not in checked:
        findings.append(Finding(
            "VP602", ERROR, name,
            "estimated VMEM footprint %.1f KiB (refs %.1f x2 double-"
            "buffered + scratch %.1f) exceeds the %.0f KiB budget — "
            "the kernel will not fit on a core"
            % (total / 1024.0, ref_bytes / 1024.0,
               scratch_bytes / 1024.0, budget / 1024.0),
            hint="shrink block_q/block_k (halving one halves its "
                 "tiles), or drop --vmem-kib if targeting a larger "
                 "part"))
    return findings


def audit_pallas_kernels(launches=None, vmem_kib=None):
    """VP6xx audit over kernel-launch descriptions — ``launches`` or,
    by default, every launch the registered kernels report for their
    CONFIGURED geometry (``ops.pallas.kernel_audit_launches()``: flash
    fwd/bwd at the site-config block sizes, paged decode at the serving
    defaults).  Pure block-shape arithmetic — nothing is compiled or
    dispatched."""
    if launches is None:
        from veles_tpu.ops import pallas
        launches = pallas.kernel_audit_launches()
    findings = []
    for launch in launches:
        findings.extend(audit_kernel_launch(launch, vmem_kib=vmem_kib))
    return findings


def audit_numerics(spec=None, launches=None, vmem_kib=None,
                   prng_registry=True):
    """The full numerics pass: VN4xx/VR500/502/503 over ``spec``'s
    staged step (when given), VR501 over the prng registry, VP6xx over
    the Pallas launches.  This is what ``lint_workflow`` and the CLI
    ``--numerics`` flag run."""
    findings = []
    if spec:
        findings.extend(audit_numerics_step(spec))
    if prng_registry:
        findings.extend(audit_prng_registry())
    findings.extend(audit_pallas_kernels(launches=launches,
                                         vmem_kib=vmem_kib))
    return findings
