"""Host-determinism audit (VB11xx).

Every chaos gate in this tree (train/pod/numerics/fleet) rests on
bit-identical restore/replay/splice at threshold 0 — and that
guarantee is only as strong as the HOST code on the compared paths:
one unsorted ``os.listdir`` feeding commit agreement, one wall-clock
value folded into a digest, one ``uuid4`` in a replayed path, and two
healthy hosts disagree about identical state.  This audit scans the
modules the gates compare bit-identically (snapshotter, loader, prng,
sentinel replay, generate splice, podmaster agreement — pure AST,
nothing is imported, nothing runs) for the host-side nondeterminism
classes.

**Scope discipline.**  The file set IS the rule's sink: these modules
produce the compared artifacts, so within them filesystem-enumeration
order, set-iteration order, host RNG, and wall-clock-into-payload are
flagged at the call site rather than through whole-program flow
tracking.  Wall-clock provenance keys every snapshot legitimately
carries (``"created"``-style) are exempted by
:data:`EXEMPT_WALLCLOCK_KEYS` — each with its rationale, rendered into
``docs/state_reference.md`` by the VK10xx reference builder's shared
:data:`~veles_tpu.analysis.state_audit.META_KEYS` table.

Rule catalog (docs/static_analysis.md):

========  =======  ======================================================
VB1100    error    wall-clock (``time.time``/``datetime.now``/
                   ``getmtime``) flowing into a serialized contract
                   payload key or a digest — equal states stamp
                   unequal (metadata keys on the exemption allowlist
                   are fine)
VB1101    error    unsorted filesystem enumeration (``os.listdir``/
                   ``glob``/``iterdir``/``scandir``/``os.walk``) —
                   directory order is filesystem-dependent, so
                   checkpoint selection/agreement built on it diverges
                   across hosts
VB1102    error    iteration over a set in the compared modules —
                   set order varies per process (hash randomization),
                   so anything built from it is host-dependent
VB1103    error    host RNG in a replayed path: module-level
                   ``random.*``, ``uuid.uuid*``, unseeded
                   ``Random()``/``RandomState()``/``default_rng()``
                   (seeded instances and ``jax.random`` are the
                   sanctioned sources)
VB1104    warning  threads spawned in a loop append into a container
                   that is then serialized/returned without an
                   ordering discipline — completion order is the
                   scheduler's, not the program's
========  =======  ======================================================

**Suppression**: ``# lint-ok: VB1101 — reason`` on the flagged line or
the contiguous comment block above it; a bare ``# lint-ok:``
suppresses nothing.
"""

import ast
import os
import re

from veles_tpu.analysis.findings import (ERROR, WARNING, Finding,
                                         sort_findings)

#: the full VB11xx family, in catalog order
RULES = ("VB1100", "VB1101", "VB1102", "VB1103", "VB1104")

_SUPPRESS_RE = re.compile(r"#\s*lint-ok:\s*([A-Z]{2}\d{3,4}(?:\s*,\s*"
                          r"[A-Z]{2}\d{3,4})*)")

#: wall-clock payload keys that are sanctioned metadata, with the
#: rationale the reference doc renders (kept in lockstep with
#: state_audit.META_KEYS — the VK1000 exemptions for the same keys)
EXEMPT_WALLCLOCK_KEYS = {
    "created": "commit wall-time provenance for operators; never read "
               "back by any restore path",
    "mtime": "host-local commit mtime used only for same-host ordering "
             "(SPMD-lockstep ties are broken by name)",
    "ts": "crash wall-time provenance for the post-mortem timeline",
}

#: functions whose dict payloads are serialized contract state (the
#: VK10xx writer surface) — VB1100's sink set
WRITER_FUNCS = ("collect", "state_manifest", "commit_meta",
                "scan_commits", "worker_spec", "_meta_state",
                "_save_locked")

_WALLCLOCK_CALLS = ("time.time", "time.time_ns", "time.monotonic",
                    "time.monotonic_ns", "datetime.now",
                    "datetime.utcnow", "datetime.datetime.now",
                    "datetime.datetime.utcnow", "os.path.getmtime",
                    "getmtime")

_ENUM_CALLS = ("os.listdir", "listdir", "os.scandir", "scandir",
               "glob.glob", "glob.iglob", "os.walk")
_ENUM_METHOD_TAILS = ("iterdir", "glob", "rglob")

#: module-level random functions (the shared-global-state API);
#: seeded instances (random.Random(seed), np.random.RandomState(seed),
#: np.random.default_rng(seed)) are the sanctioned host-side source
_RANDOM_MODULE_FNS = ("random", "randrange", "randint", "choice",
                      "choices", "shuffle", "sample", "uniform",
                      "gauss", "normalvariate", "getrandbits",
                      "betavariate", "expovariate", "seed")
_UUID_FNS = ("uuid1", "uuid3", "uuid4", "uuid5")
_NP_RANDOM_FNS = ("rand", "randn", "randint", "random", "choice",
                  "shuffle", "permutation", "normal", "uniform",
                  "seed", "random_sample")
_SEEDED_CTORS = ("Random", "RandomState", "default_rng", "Generator",
                 "PCG64")

#: files (relative to the package root) the chaos gates compare
#: bit-identically — the default scan set
DEFAULT_FILES = (
    "services/snapshotter.py",
    "services/sentinel.py",
    "services/podmaster.py",
    "prng.py",
    "models/generate.py",
    "loader/base.py",
    "loader/fullbatch.py",
    "loader/streaming.py",
    "loader/image.py",
)


def _dotted(node):
    """``a.b.c`` -> "a.b.c" (None for anything fancier)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _const_str(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class _Suppressor(object):
    """Line -> suppressed-rule lookup (the VT/VW/VC/VK semantics)."""

    def __init__(self, source):
        lines = source.splitlines()
        self._by_line = {}
        for i, line in enumerate(lines, 1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",")}
            self._by_line.setdefault(i, set()).update(rules)
            if line.lstrip().startswith("#"):
                j = i + 1
                while j <= len(lines) and \
                        lines[j - 1].lstrip().startswith("#"):
                    j += 1
                if j <= len(lines):
                    self._by_line.setdefault(j, set()).update(rules)

    def __call__(self, rule, lineno):
        return rule in self._by_line.get(lineno, ())


class _Module(object):

    def __init__(self, rel, tree, source):
        self.rel = rel
        self.tree = tree
        self.suppressed = _Suppressor(source)
        self.findings = []
        self.parents = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    def _emit(self, rule, severity, lineno, message, hint=None):
        if self.suppressed(rule, lineno):
            return
        self.findings.append(Finding(
            rule, severity, "%s:%d" % (self.rel, lineno), message,
            hint=hint))

    def _functions(self):
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                yield node

    def _in_sorted(self, node):
        """True when ``node`` sits inside a sorted()/list.sort() wrap
        (any ancestor call to sorted — covers genexp arguments)."""
        cur = node
        while cur is not None:
            if isinstance(cur, ast.Call) and \
                    isinstance(cur.func, ast.Name) and \
                    cur.func.id == "sorted" and cur is not node:
                return True
            cur = self.parents.get(cur)
        return False

    @staticmethod
    def _is_wallclock(node):
        return isinstance(node, ast.Call) and \
            (_dotted(node.func) or "") in _WALLCLOCK_CALLS

    # ------------------------------------------------------- VB1100
    def check_wallclock_payloads(self):
        hashes = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    isinstance(node.value, ast.Call) and \
                    (_dotted(node.value.func) or "") \
                    .startswith("hashlib."):
                hashes.add(node.targets[0].id)
        for func in self._functions():
            if func.name in WRITER_FUNCS:
                for node in ast.walk(func):
                    if isinstance(node, ast.Dict):
                        for k, v in zip(node.keys, node.values):
                            self._check_wallclock_value(
                                _const_str(k), v)
                    elif isinstance(node, ast.Assign) and \
                            len(node.targets) == 1 and \
                            isinstance(node.targets[0],
                                       ast.Subscript):
                        self._check_wallclock_value(
                            _const_str(node.targets[0].slice),
                            node.value)
        # wall-clock into any digest, writer function or not
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                chain = _dotted(node.func) or ""
                is_digest = chain.startswith("hashlib.") or (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "update"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in hashes)
                if is_digest and any(
                        self._is_wallclock(n) for a in node.args
                        for n in ast.walk(a)):
                    self._emit(
                        "VB1100", ERROR, node.lineno,
                        "wall-clock value folded into a digest — "
                        "equal states hash unequal across hosts/runs",
                        hint="digest only the state; keep timestamps "
                             "in exempted metadata keys")

    def _check_wallclock_value(self, key, value):
        if key is None:
            return
        if not any(self._is_wallclock(n) for n in ast.walk(value)):
            return
        if key in EXEMPT_WALLCLOCK_KEYS:
            return
        self._emit(
            "VB1100", ERROR, value.lineno,
            "wall-clock value written into serialized contract key "
            "%r — bit-compared payloads from identical state differ "
            "per run" % key,
            hint="move it to an exempted metadata key "
                 "(EXEMPT_WALLCLOCK_KEYS, with a rationale) or drop "
                 "it from the payload")

    # ------------------------------------------------------- VB1101
    def check_fs_enumeration(self):
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _dotted(node.func) or ""
            tail = chain.rsplit(".", 1)[-1]
            is_enum = chain in _ENUM_CALLS or (
                isinstance(node.func, ast.Attribute)
                and tail in _ENUM_METHOD_TAILS)
            if not is_enum:
                continue
            if self._in_sorted(node):
                continue
            self._emit(
                "VB1101", ERROR, node.lineno,
                "unsorted filesystem enumeration (%s) in a module the "
                "chaos gates compare bit-identically — directory "
                "order is filesystem-dependent, so selection/"
                "agreement built on it diverges across hosts" % tail,
                hint="wrap the call in sorted(...)")

    # ------------------------------------------------------- VB1102
    def check_set_iteration(self):
        for func in self._functions():
            set_vars = set()
            for node in ast.walk(func):
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name):
                    val = node.value
                    is_set = isinstance(val, (ast.Set, ast.SetComp)) \
                        or (isinstance(val, ast.Call)
                            and (_dotted(val.func) or "")
                            in ("set", "frozenset"))
                    if is_set:
                        set_vars.add(node.targets[0].id)
                    elif isinstance(val, ast.Call) or \
                            isinstance(val, (ast.List, ast.ListComp)):
                        set_vars.discard(node.targets[0].id)
            for node in ast.walk(func):
                if not isinstance(node, ast.For):
                    continue
                it = node.iter
                direct_set = isinstance(it, (ast.Set, ast.SetComp)) \
                    or (isinstance(it, ast.Call)
                        and (_dotted(it.func) or "")
                        in ("set", "frozenset")) \
                    or (isinstance(it, ast.Name)
                        and it.id in set_vars)
                if direct_set and not self._in_sorted(it):
                    self._emit(
                        "VB1102", ERROR, node.lineno,
                        "iteration over a set in a bit-compared "
                        "module — set order varies per process (hash "
                        "randomization), so anything built from this "
                        "loop is host-dependent",
                        hint="iterate sorted(the_set)")

    # ------------------------------------------------------- VB1103
    def check_host_rng(self):
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _dotted(node.func) or ""
            parts = chain.split(".")
            msg = None
            if len(parts) == 2 and parts[0] == "random" and \
                    parts[1] in _RANDOM_MODULE_FNS:
                msg = "module-level random.%s() shares global, " \
                      "per-process RNG state" % parts[1]
            elif len(parts) == 2 and parts[0] == "uuid" and \
                    parts[1] in _UUID_FNS:
                msg = "uuid.%s() is host/clock-derived" % parts[1]
            elif len(parts) >= 2 and parts[-2:-1] == ["random"] and \
                    parts[-1] in _NP_RANDOM_FNS and \
                    parts[0] in ("np", "numpy"):
                msg = "module-level %s() shares global RNG state" \
                    % chain
            elif parts[-1] in _SEEDED_CTORS and not node.args and \
                    not node.keywords and \
                    parts[0] in ("random", "np", "numpy"):
                msg = "unseeded %s() draws OS entropy" % chain
            if msg is None:
                continue
            self._emit(
                "VB1103", ERROR, node.lineno,
                "host RNG in a replayed path: %s — replay/splice "
                "cannot reproduce it" % msg,
                hint="thread a seeded instance (random.Random(seed), "
                     "np.random.default_rng(seed)) or jax.random keys")

    # ------------------------------------------------------- VB1104
    def check_threaded_accumulation(self):
        for func in self._functions():
            self._check_threads_in(func)

    def _check_threads_in(self, func):
        # targets of threads spawned inside a For loop
        loop_targets = set()
        for node in ast.walk(func):
            if not isinstance(node, ast.For):
                continue
            for inner in ast.walk(node):
                if isinstance(inner, ast.Call) and \
                        (_dotted(inner.func) or "") \
                        .rsplit(".", 1)[-1] == "Thread":
                    for kw in inner.keywords:
                        if kw.arg == "target" and \
                                isinstance(kw.value, ast.Name):
                            loop_targets.add(kw.value.id)
        if not loop_targets:
            return
        # containers the thread targets append into
        appended = set()
        for node in ast.walk(func):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) and \
                    node.name in loop_targets:
                for inner in ast.walk(node):
                    if isinstance(inner, ast.Call) and \
                            isinstance(inner.func, ast.Attribute) and \
                            inner.func.attr in ("append", "extend",
                                                "add") and \
                            isinstance(inner.func.value, ast.Name):
                        appended.add(inner.func.value.id)
        if not appended:
            return
        # is the shared container ordered before it escapes?
        ordered = set()
        for node in ast.walk(func):
            if isinstance(node, ast.Call):
                chain = _dotted(node.func) or ""
                if chain == "sorted" and node.args and \
                        isinstance(node.args[0], ast.Name):
                    ordered.add(node.args[0].id)
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "sort" and \
                        isinstance(node.func.value, ast.Name):
                    ordered.add(node.func.value.id)
        for node in ast.walk(func):
            sink_var, lineno = None, None
            if isinstance(node, ast.Return) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id in appended:
                sink_var, lineno = node.value.id, node.lineno
            elif isinstance(node, ast.Call):
                tail = (_dotted(node.func) or "").rsplit(".", 1)[-1]
                if tail in ("dump", "dumps", "update"):
                    for a in node.args:
                        if isinstance(a, ast.Name) and \
                                a.id in appended:
                            sink_var, lineno = a.id, node.lineno
            if sink_var is not None and sink_var not in ordered:
                self._emit(
                    "VB1104", WARNING, lineno,
                    "%r accumulates from threads spawned in a loop "
                    "and escapes into a compared/serialized result "
                    "without an ordering discipline — its order is "
                    "the scheduler's" % sink_var,
                    hint="sort it ('.sort()' / sorted(...)) before "
                         "serializing, or key results by input index")


def _parse(path, root=None):
    with open(path) as fh:
        source = fh.read()
    rel = os.path.relpath(path, root) if root else path
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return None, [Finding(
            "VB1101", ERROR, "%s:%d" % (rel, e.lineno or 0),
            "file failed to parse: %s" % e)]
    return _Module(rel, tree, source), []


def lint_determinism(paths=None, root=None):
    """VB11xx over a file set — default :data:`DEFAULT_FILES` under
    the package root (the modules the chaos gates compare
    bit-identically).  Returns sorted Findings; inline ``# lint-ok:
    VBxxxx — reason`` comments suppress accepted sites."""
    if paths is None:
        here = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        root = root or os.path.dirname(here)
        paths = [os.path.join(here, f) for f in DEFAULT_FILES]
    findings = []
    for p in paths:
        mod, errs = _parse(p, root=root)
        findings.extend(errs)
        if mod is None:
            continue
        mod.check_wallclock_payloads()
        mod.check_fs_enumeration()
        mod.check_set_iteration()
        mod.check_host_rng()
        mod.check_threaded_accumulation()
        findings.extend(mod.findings)
    return sort_findings(findings)
