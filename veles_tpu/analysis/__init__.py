"""veles_tpu.analysis — static workflow-graph linter + jit-staging,
sharding/memory and numerics/determinism auditors.

Runs over a *constructed* (not initialized) Workflow: graph rules decide
control/data-link correctness (graph_lint, VG...), the staging auditor
abstractly traces staged step functions for host-sync and recompile
hazards (staging, VJ...), the sharding/memory auditor lowers the
staged step under its device mesh and lints the collectives and the
per-device HBM picture (sharding_audit, VS2xx/VM3xx — needs an
initialized workflow with a mesh, e.g. ``veles-tpu-lint --mesh 2x2``),
and the numerics/determinism auditor walks the staged step's jaxpr for
NaN/overflow/precision hazards, PRNG misuse, and Pallas-kernel
tile/VMEM mis-sizing (numerics_audit, VN4xx/VR5xx/VP6xx — needs an
initialized workflow, e.g. ``veles-tpu-lint --numerics``).  The
serving plane has its own two families: the decode-path auditor
abstractly traces the engine's decode tick + segmented-prefill pass
(decode_audit, VD7xx — ``veles-tpu-lint --serve``) and the
concurrency lint AST-scans the threaded control plane in
``services/`` (concurrency_lint, VT8xx — ``--concurrency``).  Two
contract auditors close the loop: the wire-protocol lint checks the
control-plane line-JSON message grammar sender-vs-handler
(protocol_audit, VW9xx — ``--protocol``) and the config/telemetry
contract audit checks every ``root.common`` knob read against the
``config.py`` declarations and every flight-event/metric emit against
the test/tool/docs surface (config_audit, VC95x — ``--config-audit``,
which also generates docs/config_reference.md via ``--format
markdown``).  The state plane closes the bit-exactness loop: the
serialized-state contract auditor checks every snapshot/manifest/
winners/crashdump/spec/NDJSON key writer-vs-reader plus canonical-
serialization and picklability (state_audit, VK10xx — ``--state``,
which also generates docs/state_reference.md via ``--format
markdown``), and the host-determinism lint scans the bit-compared
modules for wall-clock, unsorted enumeration, set-order iteration,
host RNG and unordered threaded accumulation (determinism_audit,
VB11xx — ``--determinism``).  The performance plane gets the same
treatment: the target-contract lint cross-checks the declared target
registry (``telemetry.ledger.TARGETS``) against the performance
ledger's measurements both ways (perf_lint, VL12xx — ``--perf``, a
data audit of the ledger file; the runtime regression verdicts live
in ``veles-tpu-perf gate``).  ``--all`` runs every registered AST
family in one pass.  Surface: :func:`lint_workflow` in-process, the
``veles-tpu-lint`` console script, and ``python -m veles_tpu ...
--lint``.

Rule catalog and severities: docs/static_analysis.md."""

from veles_tpu.analysis.findings import (ERROR, INFO, SEVERITIES, WARNING,
                                         Finding, format_findings,
                                         has_errors, sort_findings,
                                         threshold_reached)
from veles_tpu.analysis.graph_lint import lint_graph
from veles_tpu.analysis.staging import audit_step

__all__ = ["ERROR", "WARNING", "INFO", "SEVERITIES", "Finding",
           "format_findings", "has_errors", "sort_findings",
           "threshold_reached", "lint_graph", "audit_step",
           "audit_sharded_step", "audit_numerics", "lint_workflow",
           "lint_serving", "lint_concurrency", "lint_protocol",
           "lint_config", "build_config_reference", "lint_state",
           "lint_determinism", "build_state_reference", "lint_perf"]


def audit_sharded_step(spec, hbm_gib=None):
    """Sharding/memory audit of one staged step (VS2xx/VM3xx) — see
    :mod:`veles_tpu.analysis.sharding_audit` (imported lazily: the
    graph rules must stay usable without lowering anything)."""
    from veles_tpu.analysis import sharding_audit
    return sharding_audit.audit_sharded_step(spec, hbm_gib=hbm_gib)


def audit_numerics(spec=None, launches=None, vmem_kib=None,
                   prng_registry=True):
    """Numerics/determinism/Pallas audit (VN4xx/VR5xx/VP6xx) — see
    :mod:`veles_tpu.analysis.numerics_audit` (lazy for the same
    reason)."""
    from veles_tpu.analysis import numerics_audit
    return numerics_audit.audit_numerics(
        spec=spec, launches=launches, vmem_kib=vmem_kib,
        prng_registry=prng_registry)


def lint_serving(trainer, max_len, **kwargs):
    """Decode-path audit of the serving engine (VD7xx) — see
    :mod:`veles_tpu.analysis.decode_audit` (lazy: the auditor builds
    real generators/batchers, which the graph rules never need)."""
    from veles_tpu.analysis import decode_audit
    return decode_audit.lint_serving(trainer, max_len, **kwargs)


def lint_concurrency(paths=None, root=None):
    """Concurrency lint of the threaded control plane (VT8xx) — see
    :mod:`veles_tpu.analysis.concurrency_lint` (lazy; pure AST, no
    jax)."""
    from veles_tpu.analysis import concurrency_lint
    return concurrency_lint.lint_concurrency(paths=paths, root=root)


def lint_protocol(paths=None, root=None):
    """Wire-protocol contract lint of the control-plane line-JSON
    grammar (VW9xx) — see :mod:`veles_tpu.analysis.protocol_audit`
    (lazy; pure AST, no jax)."""
    from veles_tpu.analysis import protocol_audit
    return protocol_audit.lint_protocol(paths=paths, root=root)


def lint_config(registry=None, root=None):
    """Config/telemetry contract audit (VC95x) — see
    :mod:`veles_tpu.analysis.config_audit` (lazy; pure AST, no jax)."""
    from veles_tpu.analysis import config_audit
    return config_audit.lint_config(registry=registry, root=root)


def build_config_reference(registry=None, root=None):
    """The generated docs/config_reference.md contract reference —
    see :func:`veles_tpu.analysis.config_audit.build_reference`."""
    from veles_tpu.analysis import config_audit
    return config_audit.build_reference(registry=registry, root=root)


def lint_state(paths=None, root=None):
    """Serialized-state contract audit (VK10xx) — see
    :mod:`veles_tpu.analysis.state_audit` (lazy; pure AST, no jax)."""
    from veles_tpu.analysis import state_audit
    return state_audit.lint_state(paths=paths, root=root)


def lint_determinism(paths=None, root=None):
    """Host-determinism lint of the bit-compared modules (VB11xx) —
    see :mod:`veles_tpu.analysis.determinism_audit` (lazy; pure AST,
    no jax)."""
    from veles_tpu.analysis import determinism_audit
    return determinism_audit.lint_determinism(paths=paths, root=root)


def lint_perf(ledger_path=None, targets=None, records=None):
    """Performance target-contract lint (VL12xx) — see
    :mod:`veles_tpu.analysis.perf_lint` (lazy; pure data audit of the
    ledger file, no AST, no jax)."""
    from veles_tpu.analysis import perf_lint
    return perf_lint.lint_perf(ledger_path=ledger_path,
                               targets=targets, records=records)


def build_state_reference(root=None):
    """The generated docs/state_reference.md serialized-state catalog —
    see :func:`veles_tpu.analysis.state_audit.build_reference`."""
    from veles_tpu.analysis import state_audit
    return state_audit.build_reference(root=root)


def lint_workflow(wf, staging=True, sharding=True, numerics=True,
                  hbm_gib=None, vmem_kib=None):
    """All analysis passes over ``wf``: every graph rule, the staging
    audit of any unit exposing ``lint_staging_spec()``, the
    sharding/memory audit of any unit exposing ``lint_sharding_spec()``,
    and the numerics audit of any unit exposing ``lint_numerics_spec()``
    (StagedTrainer exposes all three after initialize(); the specs are
    complementary — staging covers the single-device step, sharding the
    mesh step, numerics the step's value ranges and randomness either
    way).  The numerics pass also audits the global prng registry
    (VR501) and every registered Pallas kernel's configured launch
    geometry (VP6xx) exactly once.  Returns sorted Findings."""
    findings = lint_graph(wf)
    for unit in [wf] + list(wf.units):
        if staging:
            hook = getattr(unit, "lint_staging_spec", None)
            if callable(hook):
                spec = hook()
                if spec:   # None: no staged step yet (pre-initialize)
                    findings.extend(audit_step(
                        spec["fn"], spec.get("args", ()),
                        carry_argnums=tuple(spec.get("carry_argnums",
                                                     ())),
                        name=spec.get("name",
                                      getattr(unit, "name", "step"))))
        if sharding:
            hook = getattr(unit, "lint_sharding_spec", None)
            if callable(hook):
                spec = hook()
                if spec:   # None: no mesh, or not initialized yet
                    findings.extend(audit_sharded_step(spec,
                                                       hbm_gib=hbm_gib))
        if numerics:
            hook = getattr(unit, "lint_numerics_spec", None)
            if callable(hook):
                spec = hook()
                if spec:   # None: not initialized yet
                    from veles_tpu.analysis import numerics_audit
                    findings.extend(
                        numerics_audit.audit_numerics_step(spec))
    if numerics:
        # registry + kernel geometry are workflow-global: once, not
        # per-unit (and still audited when no unit exposes a spec)
        findings.extend(audit_numerics(
            None, vmem_kib=vmem_kib, prng_registry=True))
    return sort_findings(findings)
