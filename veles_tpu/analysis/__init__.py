"""veles_tpu.analysis — static workflow-graph linter + jit-staging +
sharding/memory auditors.

Runs over a *constructed* (not initialized) Workflow: graph rules decide
control/data-link correctness (graph_lint, VG...), the staging auditor
abstractly traces staged step functions for host-sync and recompile
hazards (staging, VJ...), and the sharding/memory auditor lowers the
staged step under its device mesh and lints the collectives and the
per-device HBM picture (sharding_audit, VS2xx/VM3xx — needs an
initialized workflow with a mesh, e.g. ``veles-tpu-lint --mesh 2x2``).
Surface: :func:`lint_workflow` in-process, the ``veles-tpu-lint``
console script, and ``python -m veles_tpu ... --lint``.

Rule catalog and severities: docs/static_analysis.md."""

from veles_tpu.analysis.findings import (ERROR, INFO, SEVERITIES, WARNING,
                                         Finding, format_findings,
                                         has_errors, sort_findings)
from veles_tpu.analysis.graph_lint import lint_graph
from veles_tpu.analysis.staging import audit_step

__all__ = ["ERROR", "WARNING", "INFO", "SEVERITIES", "Finding",
           "format_findings", "has_errors", "sort_findings", "lint_graph",
           "audit_step", "audit_sharded_step", "lint_workflow"]


def audit_sharded_step(spec, hbm_gib=None):
    """Sharding/memory audit of one staged step (VS2xx/VM3xx) — see
    :mod:`veles_tpu.analysis.sharding_audit` (imported lazily: the
    graph rules must stay usable without lowering anything)."""
    from veles_tpu.analysis import sharding_audit
    return sharding_audit.audit_sharded_step(spec, hbm_gib=hbm_gib)


def lint_workflow(wf, staging=True, sharding=True, hbm_gib=None):
    """All analysis passes over ``wf``: every graph rule, the staging
    audit of any unit exposing ``lint_staging_spec()``, and the
    sharding/memory audit of any unit exposing ``lint_sharding_spec()``
    (e.g. StagedTrainer after initialize() under a mesh — the two hooks
    are complementary: the staging hook covers the single-device step,
    the sharding hook the mesh step).  Returns sorted Findings."""
    findings = lint_graph(wf)
    for unit in [wf] + list(wf.units):
        if staging:
            hook = getattr(unit, "lint_staging_spec", None)
            if callable(hook):
                spec = hook()
                if spec:   # None: no staged step yet (pre-initialize)
                    findings.extend(audit_step(
                        spec["fn"], spec.get("args", ()),
                        carry_argnums=tuple(spec.get("carry_argnums",
                                                     ())),
                        name=spec.get("name",
                                      getattr(unit, "name", "step"))))
        if sharding:
            hook = getattr(unit, "lint_sharding_spec", None)
            if callable(hook):
                spec = hook()
                if spec:   # None: no mesh, or not initialized yet
                    findings.extend(audit_sharded_step(spec,
                                                       hbm_gib=hbm_gib))
    return sort_findings(findings)
