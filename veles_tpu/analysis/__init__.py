"""veles_tpu.analysis — static workflow-graph linter + jit-staging auditor.

Runs over a *constructed* (not initialized) Workflow: graph rules decide
control/data-link correctness (graph_lint, VG...), the staging auditor
abstractly traces staged step functions for host-sync and recompile
hazards (staging, VJ...).  Surface: :func:`lint_workflow` in-process, the
``veles-tpu-lint`` console script, and ``python -m veles_tpu ... --lint``.

Rule catalog and severities: docs/static_analysis.md."""

from veles_tpu.analysis.findings import (ERROR, INFO, SEVERITIES, WARNING,
                                         Finding, format_findings,
                                         has_errors, sort_findings)
from veles_tpu.analysis.graph_lint import lint_graph
from veles_tpu.analysis.staging import audit_step

__all__ = ["ERROR", "WARNING", "INFO", "SEVERITIES", "Finding",
           "format_findings", "has_errors", "sort_findings", "lint_graph",
           "audit_step", "lint_workflow"]


def lint_workflow(wf, staging=True):
    """All analysis passes over ``wf``: every graph rule, plus the staging
    audit of any unit exposing a ``lint_staging_spec()`` hook (e.g.
    StagedTrainer after initialize()).  Returns sorted Findings."""
    findings = lint_graph(wf)
    if staging:
        for unit in [wf] + list(wf.units):
            hook = getattr(unit, "lint_staging_spec", None)
            if not callable(hook):
                continue
            spec = hook()
            if not spec:
                continue  # unit has no staged step yet (pre-initialize)
            findings.extend(audit_step(
                spec["fn"], spec.get("args", ()),
                carry_argnums=tuple(spec.get("carry_argnums", ())),
                name=spec.get("name", getattr(unit, "name", "step"))))
    return sort_findings(findings)
