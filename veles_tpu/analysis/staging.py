"""Jit-staging auditor: abstract tracing of a staged step function.

A workflow's hot loop is staged into one jitted step (workflow.py design
note), so anything host-side that leaks into that step is a silent 100×
perf bug: a callback forces a device→host sync every iteration, a
weak-typed python scalar in the signature recompiles on promotion, and a
carry whose dtype/shape drifts between iterations recompiles every single
step.  All three are visible in the jaxpr *without running anything* —
``jax.make_jaxpr`` over ``jax.ShapeDtypeStruct`` inputs traces abstractly
(the pattern of parallel/pipeline.py's ``jax.eval_shape`` probe and
nn_units' abstract optimizer-slot spec).

Rule catalog (docs/static_analysis.md):

========  ========  =====================================================
VJ100     error     the step failed to trace abstractly at all
VJ101     error     host callback primitive in the hot path
                    (``debug_print`` / ``pure_callback`` / ``io_callback``)
VJ102     warning   weak-typed input: a python scalar leaked into the
                    step signature (promotion → recompile hazard)
VJ103     error     carry aval drift: an output that feeds the next
                    iteration differs in shape/dtype/weak-type from the
                    input it replaces (recompile every iteration)
========  ========  =====================================================
"""

import jax

from veles_tpu.analysis.findings import ERROR, WARNING, Finding

#: primitive names that force a device→host round trip mid-step
_HOST_SYNC_PRIMS = ("outfeed", "infeed")


def _sub_jaxprs(value):
    """Nested jaxprs hiding in an eqn's params (pjit/scan/while carry a
    ClosedJaxpr under 'jaxpr', cond a list under 'branches', custom
    primitives stash them in dicts — e.g. keyed branch/function tables),
    so a container-valued param never hides a VJ101 host callback."""
    if hasattr(value, "jaxpr"):          # ClosedJaxpr
        return [value.jaxpr]
    if hasattr(value, "eqns"):           # bare Jaxpr
        return [value]
    if isinstance(value, dict):
        out = []
        for v in value.values():
            out.extend(_sub_jaxprs(v))
        return out
    if isinstance(value, (list, tuple)):
        out = []
        for v in value:
            out.extend(_sub_jaxprs(v))
        return out
    return []


def iter_primitives(jaxpr):
    """Yield every (primitive_name, eqn) in ``jaxpr``, recursing into
    sub-jaxprs of higher-order primitives."""
    for eqn in jaxpr.eqns:
        yield eqn.primitive.name, eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from iter_primitives(sub)


def _aval_str(aval):
    weak = ", weak" if getattr(aval, "weak_type", False) else ""
    return "%s[%s]%s" % (getattr(aval, "dtype", "?"),
                         ",".join(map(str, getattr(aval, "shape", ()))),
                         weak)


def _avals_equal(a, b):
    return (getattr(a, "shape", None) == getattr(b, "shape", None)
            and getattr(a, "dtype", None) == getattr(b, "dtype", None)
            and bool(getattr(a, "weak_type", False))
            == bool(getattr(b, "weak_type", False)))


def audit_step(fn, args=(), *, carry_argnums=(), name="step"):
    """Abstractly trace ``fn(*args)`` and return staging Findings.

    ``args`` may be concrete arrays, pytrees, or ``jax.ShapeDtypeStruct``
    specs — tracing never touches a device.  ``carry_argnums`` names the
    positional args that the step's outputs replace on the next iteration
    (e.g. ``(0, 1, 2)`` for ``(params, velocity, acc) -> (params,
    velocity, acc)``); their avals are compared against the outputs for
    the VJ103 recompile-every-iteration hazard."""
    findings = []
    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as e:  # noqa: BLE001 — any trace failure is the finding
        return [Finding(
            "VJ100", ERROR, name,
            "staged step failed to trace abstractly: %s: %s"
            % (type(e).__name__, e),
            hint="the step must be traceable with abstract inputs — "
                 "no data-dependent python control flow or host state")]

    # ---- VJ101: host callbacks / host syncs in the hot path
    seen = set()
    for prim_name, _eqn in iter_primitives(closed.jaxpr):
        if "callback" not in prim_name \
                and prim_name not in _HOST_SYNC_PRIMS:
            continue
        if prim_name in seen:
            continue
        seen.add(prim_name)
        what = ("jax.debug.print/debug.callback"
                if prim_name == "debug_callback" else prim_name)
        findings.append(Finding(
            "VJ101", ERROR, name,
            "host callback in the hot path (%s): every iteration "
            "round-trips device -> host, serializing the XLA stream"
            % what,
            hint="move host work (printing, logging, numpy) outside the "
                 "staged step; fetch stats from the step's outputs "
                 "instead"))

    # ---- VJ102: weak-typed inputs (python scalars in the signature)
    for i, aval in enumerate(closed.in_avals):
        if getattr(aval, "weak_type", False):
            findings.append(Finding(
                "VJ102", WARNING, name,
                "input leaf %d is weak-typed (%s): a python scalar "
                "leaked into the step signature — promotion rules "
                "change downstream dtypes and a later strongly-typed "
                "call recompiles" % (i, _aval_str(aval)),
                hint="wrap host scalars before the call, e.g. "
                     "jnp.float32(x) / jnp.asarray(x, dtype)"))

    # ---- VJ103: carry aval drift across iterations
    if carry_argnums:
        flat_args = [jax.tree_util.tree_leaves(a) for a in args]
        offsets = []
        pos = 0
        for leaves in flat_args:
            offsets.append(pos)
            pos += len(leaves)
        expected = []
        for argnum in carry_argnums:
            n = len(flat_args[argnum])
            expected.extend(
                closed.in_avals[offsets[argnum]:offsets[argnum] + n])
        outs = closed.out_avals
        if len(outs) != len(expected):
            findings.append(Finding(
                "VJ103", ERROR, name,
                "carry structure mismatch: the step returns %d output "
                "leaves but the carry args hold %d — the next "
                "iteration cannot reuse the compiled step"
                % (len(outs), len(expected)),
                hint="return exactly the updated carry args (same "
                     "pytree structure) from the step"))
        else:
            for i, (inp, out) in enumerate(zip(expected, outs)):
                if _avals_equal(inp, out):
                    continue
                findings.append(Finding(
                    "VJ103", ERROR, name,
                    "carry leaf %d drifts across iterations: fed in as "
                    "%s, comes out as %s — every iteration recompiles "
                    "the step" % (i, _aval_str(inp), _aval_str(out)),
                    hint="pin the carry dtype (e.g. x.astype(...) "
                         "before returning, or make the initial carry "
                         "match the steady-state dtype)"))
    return findings
