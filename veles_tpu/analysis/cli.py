"""veles-tpu-lint — build a workflow file's graph and statically lint it.

Honors the module contract (``run(load, main)``, ref __main__.py): the
workflow file constructs its Workflow through ``load(...)``; ``main()``
here is a no-op, so nothing is initialized, no XLA computation is
dispatched, and no data is loaded beyond what construction itself does.
With ``--mesh`` the workflow IS additionally initialized (on a virtual
CPU device mesh — parameters are allocated, but no training step ever
runs) so the sharding/memory auditor can lower the real staged step
under the mesh (VS2xx/VM3xx, docs/static_analysis.md).

Exit status: 0 = no findings at or above the ``--fail-on`` severity
threshold (default ``error``), 1 = threshold reached (``--fail-on
warning`` lets CI gate on warnings too), 2 = usage."""

import argparse
import os
import re
import runpy
import sys


def build_workflow(workflow_path, config_path=None, config_list=()):
    """Construct (but never initialize or run) the workflow a file
    defines, applying config layering exactly like the training CLI."""
    from veles_tpu.config import root
    from veles_tpu.genetics.core import Range
    if config_path:
        scope = {"root": root, "Range": Range}
        with open(config_path) as f:
            exec(compile(f.read(), config_path, "exec"), scope)
    for stmt in config_list:
        exec(stmt, {"root": root, "Range": Range})

    wf_globals = runpy.run_path(workflow_path, run_name="__veles__")
    if "run" not in wf_globals:
        raise SystemExit("%s does not define run(load, main)"
                         % workflow_path)
    built = {}

    def load(cls, **kwargs):
        built["wf"] = cls(**kwargs)
        return built["wf"]

    def main(**kwargs):
        return built.get("wf")  # lint never initializes or runs

    wf_globals["run"](load, main)
    if "wf" not in built:
        raise SystemExit("%s never called load(WorkflowClass, ...)"
                         % workflow_path)
    return built["wf"]


def parse_mesh(spec):
    """``'2x2'`` (data x model) or the training CLI's ``'data=2,model=2'``
    axis grammar → ``{axis: size}`` — the ONE mesh-spec parser
    (``__main__.Main._parse_mesh`` delegates here)."""
    if "=" not in spec:
        parts = spec.lower().replace("*", "x").split("x")
        if len(parts) != 2:
            raise SystemExit("--mesh wants DxM (e.g. 2x2) or "
                             "axis=size[,axis=size...], got %r" % spec)
        try:
            return {"data": int(parts[0]), "model": int(parts[1])}
        except ValueError:
            raise SystemExit("--mesh: %r is not DxM" % spec)
    axes = {}
    for part in spec.split(","):
        name, _, size = part.partition("=")
        if not size:
            raise SystemExit("--mesh wants axis=size, got %r" % part)
        try:
            axes[name.strip()] = int(size)
        except ValueError:
            raise SystemExit("--mesh: size in %r is not an integer"
                             % part)
    return axes


_DEVCOUNT_RE = re.compile(
    r"--xla_force_host_platform_device_count=(\d+)")


def _force_cpu_devices(axes):
    """Linting must never grab an accelerator, and a mesh lint needs
    enough virtual CPU devices to build the mesh — both are env knobs
    that only work before the jax backend initializes (the
    tests/conftest.py pattern).  An XLA_FLAGS pin SMALLER than the mesh
    is raised to fit; a larger one is left alone."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    n = 1
    for size in (axes or {}).values():
        if size > 0:
            n *= size
    flags = os.environ.get("XLA_FLAGS", "")
    if n > 1:
        m = _DEVCOUNT_RE.search(flags)
        if m is None:
            flags = (flags + " --xla_force_host_platform_device_count"
                     "=%d" % n).strip()
        elif int(m.group(1)) < n:
            flags = _DEVCOUNT_RE.sub(
                "--xla_force_host_platform_device_count=%d" % n, flags)
        os.environ["XLA_FLAGS"] = flags
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — backend already initialized: too
        pass           # late to repoint, construction won't dispatch


def _initialize_plain(wf):
    """Initialize the workflow on the (forced-CPU) default device so
    the staged steps exist for the numerics auditor — parameters are
    allocated, no training step ever dispatches (the ``--mesh``
    contract, minus the mesh)."""
    if not getattr(wf, "_initialized", False):
        wf.initialize()


def _attach_mesh(wf, axes, fsdp):
    """Build the MeshConfig and initialize the workflow under it (the
    Launcher's --mesh wiring, minus services/distributed): params are
    allocated on the virtual CPU mesh so the staged steps and their
    shardings exist for the auditor — still no training dispatch."""
    from veles_tpu.parallel import MeshConfig, make_mesh
    mc = MeshConfig(make_mesh(axes), fsdp=fsdp)
    for unit in [wf] + list(wf.units):
        if hasattr(unit, "mesh_config") and \
                getattr(unit, "mesh_config") is None:
            unit.mesh_config = mc
    trainer = getattr(wf, "trainer", None)
    loader = getattr(wf, "loader", None)
    if (trainer is not None and loader is not None
            and getattr(trainer, "dataset_placement", None) == "shard"
            and mc.data_size > 1
            and getattr(loader, "on_device", None) is True):
        loader.on_device = "defer"   # never materialize a full replica
    wf.initialize()
    return mc


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="veles-tpu-lint",
        description="static workflow-graph linter + jit-staging auditor "
                    "+ sharding/memory auditor + numerics/determinism "
                    "auditor + serving decode-path auditor + "
                    "control-plane concurrency lint + wire-protocol "
                    "contract lint + config/telemetry contract audit "
                    "+ serialized-state contract audit + "
                    "host-determinism lint "
                    "(rule catalog: docs/static_analysis.md)",
        formatter_class=argparse.RawDescriptionHelpFormatter,
        epilog="exit codes (identical across every family, VG...VB — "
               "analysis.findings\n.threshold_reached is the one "
               "gate):\n"
               "  0  no findings at or above the --fail-on severity\n"
               "  1  threshold reached (default --fail-on error: any "
               "error finding)\n"
               "  2  usage error (bad arguments, workflow file without "
               "run(load, main))")
    p.add_argument("workflow", nargs="?", default=None,
                   help="workflow .py file defining run(load, main) "
                   "(optional only for a pure --concurrency / "
                   "--protocol / --config-audit / --state / "
                   "--determinism / --all run — the AST lints need no "
                   "workflow)")
    p.add_argument("config", nargs="?", help="config .py file executed "
                   "with `root` in scope")
    p.add_argument("--config-list", nargs="*", default=[],
                   help="inline config statements, e.g. "
                   "'root.mnist.lr=0.1'")
    p.add_argument("--format", choices=("text", "json", "markdown"),
                   default="text",
                   help="'text'/'json' render findings; 'markdown' "
                   "(only with --config-audit alone or --state alone) "
                   "prints the generated contract reference "
                   "(docs/config_reference.md or docs/"
                   "state_reference.md) instead and always exits 0")
    p.add_argument("--no-staging", action="store_true",
                   help="graph rules only; skip the jit-staging audit "
                   "hooks")
    p.add_argument("--mesh", default=None, metavar="DxM",
                   help="initialize the workflow under a DATAxMODEL "
                   "device mesh (virtual CPU devices) and run the "
                   "VS2xx/VM3xx sharding & memory audit of the staged "
                   "step; also accepts the training CLI's "
                   "'data=2,model=2' axis grammar")
    p.add_argument("--fsdp", action="store_true",
                   help="audit with ZeRO-3 fully-sharded parameters "
                   "over the data axis (pairs with --mesh)")
    p.add_argument("--numerics", action="store_true",
                   help="initialize the workflow (params allocate, no "
                   "step dispatches — composes with --mesh) so the "
                   "VN4xx/VR5xx numerics & determinism audit can trace "
                   "the real staged train step; the prng-registry "
                   "(VR501) and Pallas kernel-geometry (VP6xx) rules "
                   "run even without this flag")
    p.add_argument("--vmem-kib", type=float, default=None, metavar="KiB",
                   help="per-core VMEM budget the VP602 Pallas kernel "
                   "footprint is judged against (default: "
                   "numerics_audit.DEFAULT_VMEM_KIB = 16384, ~16 MiB)")
    p.add_argument("--hbm-gib", type=float, default=None, metavar="GiB",
                   help="per-device HBM capacity the VM300 peak "
                   "estimate is judged against (default: "
                   "sharding_audit.DEFAULT_HBM_GIB = 16, v5e)")
    p.add_argument("--serve", action="store_true",
                   help="initialize the workflow and run the VD7xx "
                   "decode-path audit over the serving engine's decode "
                   "tick + segmented-prefill pass for every standard "
                   "variant (bf16/int8/w4a8 x dense/paged x spec "
                   "on/off) — abstract traces only, no decode step "
                   "ever dispatches")
    p.add_argument("--serve-max-len", type=int, default=16,
                   metavar="T", help="sequence budget the --serve "
                   "audit builds its generators with (default 16 — "
                   "geometry-relevant rules scale with it)")
    p.add_argument("--concurrency", action="store_true",
                   help="run the VT8xx concurrency lint (pure AST "
                   "scan) over the threaded control plane in "
                   "veles_tpu/services — needs no workflow file")
    p.add_argument("--protocol", action="store_true",
                   help="run the VW9xx wire-protocol contract lint "
                   "(pure AST scan) over the control-plane line-JSON "
                   "protocol in veles_tpu/services — every message "
                   "kind needs a sender AND a handler, state-mutating "
                   "handlers must consult the incarnation fence, "
                   "socket reads need timeout bounds; needs no "
                   "workflow file")
    p.add_argument("--config-audit", action="store_true",
                   dest="config_audit",
                   help="run the VC95x config/telemetry contract audit "
                   "(pure AST scan) over the whole tree — root.common "
                   "knob reads vs the config.py declarations (typos, "
                   "dead knobs, conflicting defaults) and flight-event"
                   "/metric emits vs the test/tool/docs surface; "
                   "needs no workflow file")
    p.add_argument("--state", action="store_true",
                   help="run the VK10xx serialized-state contract "
                   "audit (pure AST scan) over the snapshot/manifest/"
                   "winners/crashdump/fleet-spec/NDJSON state plane — "
                   "every serialized key needs a reader, every read "
                   "key a writer, optional keys a .get default or "
                   "version guard, digests canonical serialization, "
                   "pickled payloads picklable leaves; needs no "
                   "workflow file")
    p.add_argument("--determinism", action="store_true",
                   help="run the VB11xx host-determinism lint (pure "
                   "AST scan) over the modules the chaos gates "
                   "bit-compare (snapshotter/sentinel/podmaster/prng/"
                   "generate/loaders) — wall-clock into payloads or "
                   "digests, unsorted filesystem enumeration, "
                   "set-order iteration, host random/uuid, unordered "
                   "threaded accumulation; needs no workflow file")
    p.add_argument("--perf", action="store_true",
                   help="run the VL12xx performance target-contract "
                   "lint over the performance ledger (telemetry."
                   "ledger): targets declared but never measured, "
                   "measurements referencing unknown targets, "
                   "duplicate/conflicting declarations — a data "
                   "audit of the ledger file, not an AST scan; "
                   "needs no workflow file (--ledger picks the "
                   "file; sentinel verdicts live in veles-tpu-perf "
                   "gate)")
    p.add_argument("--ledger", default=None, metavar="PATH",
                   help="ledger JSONL the --perf lint reads "
                   "(default: the checked-in PERF_LEDGER.jsonl at "
                   "the repo root when present, else "
                   "root.common.perf.ledger > "
                   "VELES_TPU_PERF_LEDGER > <dirs.cache>/"
                   "perf_ledger.jsonl)")
    p.add_argument("--all", action="store_true",
                   help="run every registered AST family in one pass "
                   "(--concurrency --protocol --config-audit --state "
                   "--determinism) with one merged findings report "
                   "and one exit gate; with a workflow file the "
                   "graph/staging families run too")
    p.add_argument("--fail-on", choices=("error", "warning"),
                   default="error", metavar="{error,warning}",
                   help="severity threshold for the non-zero exit: "
                   "'error' (default) fails only on error findings, "
                   "'warning' fails on warnings too — the CI gate "
                   "knob, shared by every family (VG/VJ/VS/VM/VN/VR/"
                   "VP/VD/VT/VW/VC) through findings.threshold_reached")
    p.add_argument("--strict", action="store_true",
                   help="deprecated alias for --fail-on warning")
    args = p.parse_args(argv)

    if args.all:
        args.concurrency = args.protocol = args.config_audit = True
        args.state = args.determinism = True
    ast_only = (args.concurrency or args.protocol or args.config_audit
                or args.state or args.determinism or args.perf)
    if args.workflow is None and not ast_only:
        p.error("a workflow file is required (only pure --concurrency/"
                "--protocol/--config-audit/--state/--determinism/--all "
                "runs work without one)")
    if args.serve and args.workflow is None:
        p.error("--serve audits a workflow's serving engine — give "
                "it the workflow file")
    if args.format == "markdown":
        only_config = (args.config_audit and not args.state)
        only_state = (args.state and not args.config_audit)
        if args.workflow is not None or args.concurrency \
                or args.protocol or args.determinism \
                or not (only_config or only_state):
            p.error("--format markdown prints a generated contract "
                    "reference — it pairs with --config-audit alone "
                    "(docs/config_reference.md) or --state alone "
                    "(docs/state_reference.md)")
        if only_state:
            from veles_tpu.analysis.state_audit import build_reference
        else:
            from veles_tpu.analysis.config_audit import build_reference
        sys.stdout.write(build_reference())
        return 0

    findings = []
    if args.workflow is not None:
        axes = parse_mesh(args.mesh) if args.mesh else None
        if args.fsdp and not axes:
            raise SystemExit("--fsdp needs --mesh (parameters shard "
                             "over the mesh's data axis)")
        # env knobs must land before anything touches a jax backend
        _force_cpu_devices(axes)

        from veles_tpu.analysis import lint_serving, lint_workflow
        wf = build_workflow(args.workflow, args.config,
                            args.config_list)
        if axes:
            _attach_mesh(wf, axes, args.fsdp)
        elif args.numerics or args.serve:
            _initialize_plain(wf)
        findings.extend(lint_workflow(wf, staging=not args.no_staging,
                                      hbm_gib=args.hbm_gib,
                                      vmem_kib=args.vmem_kib))
        if args.serve:
            trainer = getattr(wf, "trainer", None)
            if trainer is None:
                raise SystemExit("--serve: workflow has no .trainer "
                                 "unit to build a serving engine from")
            findings.extend(lint_serving(trainer, args.serve_max_len,
                                         vmem_kib=args.vmem_kib))
    if args.concurrency:
        from veles_tpu.analysis import lint_concurrency
        findings.extend(lint_concurrency())
    if args.protocol:
        from veles_tpu.analysis import lint_protocol
        findings.extend(lint_protocol())
    if args.config_audit:
        from veles_tpu.analysis import lint_config
        findings.extend(lint_config())
    if args.state:
        from veles_tpu.analysis import lint_state
        findings.extend(lint_state())
    if args.determinism:
        from veles_tpu.analysis import lint_determinism
        findings.extend(lint_determinism())
    if args.perf:
        from veles_tpu.analysis import lint_perf
        ledger_path = args.ledger
        if ledger_path is None:
            # the tree-level contract judges the checked-in silicon
            # history, not whatever this box's process ledger holds
            seed = os.path.join(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
                "PERF_LEDGER.jsonl")
            if os.path.exists(seed):
                ledger_path = seed
        findings.extend(lint_perf(ledger_path=ledger_path))

    from veles_tpu.analysis import (format_findings, sort_findings,
                                    threshold_reached)
    findings = sort_findings(findings)
    print(format_findings(findings, args.format))
    fail_on = ("warning" if args.strict else args.fail_on)
    return 1 if threshold_reached(findings, fail_on) else 0


if __name__ == "__main__":
    sys.exit(main())
