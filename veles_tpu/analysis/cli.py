"""veles-tpu-lint — build a workflow file's graph and statically lint it.

Honors the module contract (``run(load, main)``, ref __main__.py): the
workflow file constructs its Workflow through ``load(...)``; ``main()``
here is a no-op, so nothing is initialized, no XLA computation is
dispatched, and no data is loaded beyond what construction itself does.
Exit status: 0 = no error-severity findings, 1 = errors (2 = usage)."""

import argparse
import runpy
import sys


def build_workflow(workflow_path, config_path=None, config_list=()):
    """Construct (but never initialize or run) the workflow a file
    defines, applying config layering exactly like the training CLI."""
    from veles_tpu.config import root
    from veles_tpu.genetics.core import Range
    if config_path:
        scope = {"root": root, "Range": Range}
        with open(config_path) as f:
            exec(compile(f.read(), config_path, "exec"), scope)
    for stmt in config_list:
        exec(stmt, {"root": root, "Range": Range})

    wf_globals = runpy.run_path(workflow_path, run_name="__veles__")
    if "run" not in wf_globals:
        raise SystemExit("%s does not define run(load, main)"
                         % workflow_path)
    built = {}

    def load(cls, **kwargs):
        built["wf"] = cls(**kwargs)
        return built["wf"]

    def main(**kwargs):
        return built.get("wf")  # lint never initializes or runs

    wf_globals["run"](load, main)
    if "wf" not in built:
        raise SystemExit("%s never called load(WorkflowClass, ...)"
                         % workflow_path)
    return built["wf"]


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="veles-tpu-lint",
        description="static workflow-graph linter + jit-staging auditor "
                    "(rule catalog: docs/static_analysis.md)")
    p.add_argument("workflow", help="workflow .py file defining "
                   "run(load, main)")
    p.add_argument("config", nargs="?", help="config .py file executed "
                   "with `root` in scope")
    p.add_argument("--config-list", nargs="*", default=[],
                   help="inline config statements, e.g. "
                   "'root.mnist.lr=0.1'")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--no-staging", action="store_true",
                   help="graph rules only; skip the jit-staging audit "
                   "hooks")
    p.add_argument("--strict", action="store_true",
                   help="exit non-zero on warnings too")
    args = p.parse_args(argv)

    import os
    # linting must never grab an accelerator: abstract tracing is
    # backend-independent, and a lint in CI shares machines with jobs
    # that do own the chips.  jax froze its env snapshot when this
    # module's imports pulled it in, so set the live config too (the
    # tests/conftest.py pattern); env covers any subprocesses
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — backend already initialized: too
        pass           # late to repoint, construction won't dispatch

    from veles_tpu.analysis import (WARNING, format_findings, has_errors,
                                    lint_workflow)
    wf = build_workflow(args.workflow, args.config, args.config_list)
    findings = lint_workflow(wf, staging=not args.no_staging)
    print(format_findings(findings, args.format))
    failed = has_errors(findings) or (
        args.strict and any(f.severity == WARNING for f in findings))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
