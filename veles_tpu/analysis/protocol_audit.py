"""Wire-protocol contract audit for the line-JSON control plane (VW9xx).

The pod master, fleet master, agents, router and supervisor speak a
stringly-typed protocol: newline-delimited JSON dicts whose ``"type"``
key names the message.  Nothing checks that contract — a kind sent
with no handler is silently dropped by the inbox pump, a renamed field
is a ``KeyError`` in a survivor mid-restart, a handler added without
the incarnation fence re-admits exactly the zombies PR 9 fenced out.
This audit extracts the whole message space from source (pure AST —
nothing is imported, nothing runs) and checks both sides of the wire
against each other.

**Extraction model.**  A *message site* is a dict literal with a
constant ``"type"`` key and a constant string value (the protocol's
construction idiom — ``conn.send({"type": "welcome", ...})``,
``return {"type": "spawn", ...}``); dicts using a ``"kind"``/``"cmd"``
discriminator count only when passed directly to a ``send``/``_send``
helper.  A *handler* is a string compared (``==``/``!=``/``in``)
against a type-expression: ``msg.get("type")`` / ``msg["type"]``, a
variable assigned from one, or the kind-parameter of a dispatch
function (``_handle_event(self, kind, host, msg)``).  The default of
``msg.get("type", "garbage")`` also registers a handled kind (the
inbox pump's classification).  Handler *branches* close over
same-class method calls the message flows into (``self._handle_spawn
(msg)``), so field/response/fence checks see the real handler body.

Rule catalog (docs/static_analysis.md):

========  =======  ======================================================
VW900     error    message kind emitted (a message site constructs it)
                   with no registered handler anywhere in the scanned
                   tree — the send is a silent no-op on the peer
VW901     error    handler branch subscripts a field (``msg["x"]``) no
                   sender of that kind ever sets — a KeyError waiting
                   for that message
VW902     error    request-shaped kind (``fetch_*``/``report_*``/
                   ``push_*``/``query_*``/``get_*``/``request*``) whose
                   handler closure never sends a response — the
                   requester waits forever
VW903     error    in a class owning an incarnation fence, a handler
                   branch reads the message's ``incarnation`` and
                   mutates state without consulting the fence (no
                   fence-attr use, no incarnation comparison) — the
                   PR 9 zombie-readmission class, machine-checked
VW904     warning  unbounded control-plane socket: ``settimeout(None)``,
                   ``create_connection`` without a timeout, or a bare
                   ``accept()`` outside a ``try/except OSError`` —
                   a dead peer parks the thread forever
VW905     error    ``json.loads`` of wire input (socket/HTTP read or a
                   wire-named parameter) with no ``ValueError``-
                   catching guard at the site or around every caller —
                   one torn line kills the owning thread
========  =======  ======================================================

**Suppression**: ``# lint-ok: VW904 — reason`` on the flagged line or
the contiguous comment block above it, exactly as for VT8xx; a bare
``# lint-ok:`` suppresses nothing.
"""

import ast
import os
import re

from veles_tpu.analysis.findings import (ERROR, WARNING, Finding,
                                         sort_findings)

#: the full VW9xx family, in catalog order
RULES = ("VW900", "VW901", "VW902", "VW903", "VW904", "VW905")

_SUPPRESS_RE = re.compile(r"#\s*lint-ok:\s*([A-Z]{2}\d{3}(?:\s*,\s*"
                          r"[A-Z]{2}\d{3})*)")

#: message discriminator keys, strongest first — "type" is the line-JSON
#: protocol's key; "kind"/"cmd" only count at direct send-helper calls
_DISCRIMINATORS = ("type", "kind", "cmd")
_SEND_TAILS = ("send", "_send")
_REQUEST_RE = re.compile(r"^(fetch|report|push|query|get|request)_"
                         r"|request")
_KIND_PARAMS = ("kind", "type", "cmd", "mtype", "msg_type")
_MSG_PARAMS = ("msg", "message", "payload", "ev", "event")
_WIRE_PARAMS = ("body", "line", "raw", "payload", "wire")
_WIRE_READ_TAILS = ("readline", "recv", "recv_into")
_WIRE_READ_ROOTS = ("rfile", "sock", "conn", "resp", "response", "wfile")
_JSON_GUARDS = ("ValueError", "JSONDecodeError", "Exception",
                "BaseException")
_SOCKET_GUARDS = ("OSError", "error", "Exception", "BaseException")
_MUTATORS = ("append", "add", "pop", "popleft", "appendleft", "remove",
             "clear", "update", "extend", "setdefault", "discard",
             "insert")


def _dotted(node):
    """``a.b.c`` -> "a.b.c" (None for anything fancier)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _const_str(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _const_strs(node):
    """Constant string, or tuple/list of them, -> list (else None)."""
    s = _const_str(node)
    if s is not None:
        return [s]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = [_const_str(el) for el in node.elts]
        if all(v is not None for v in out):
            return out
    return None


def _terminates(stmts):
    """Last statement unconditionally leaves the enclosing block."""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


class _MsgSite(object):
    """One message-construction site: a literal protocol dict."""

    def __init__(self, kind, fields, open_, lineno):
        self.kind = kind
        self.fields = set(fields)
        self.open = open_          # non-literal keys: field set unknown
        self.lineno = lineno


class _Branch(object):
    """One handler branch: the statements that run for one kind."""

    def __init__(self, kind, body, msgvar, klass, func, lineno):
        self.kind = kind
        self.body = body
        self.msgvar = msgvar       # name the message flows in under
        self.klass = klass         # _ClassInfo or None
        self.func = func           # enclosing function name
        self.lineno = lineno


class _ClassInfo(object):
    def __init__(self, name):
        self.name = name
        self.methods = {}          # method name -> FunctionDef
        self.fence_attr = None     # e.g. "fence" (IncarnationFence)


def _type_expr_target(node, typevars):
    """The message variable a type-expression reads, or ``None`` when
    ``node`` is not a type-expression.  Returns ``""`` for a
    type-expression over a non-Name message (still a dispatch site,
    but field checks are skipped)."""
    if isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr == "get" and node.args and \
            _const_str(node.args[0]) in _DISCRIMINATORS:
        base = node.func.value
        return base.id if isinstance(base, ast.Name) else ""
    if isinstance(node, ast.Subscript) and \
            _const_str(node.slice) in _DISCRIMINATORS:
        base = node.value
        return base.id if isinstance(base, ast.Name) else ""
    if isinstance(node, ast.Name) and node.id in typevars:
        return typevars[node.id]
    return None


class _FuncScan(object):
    """Handler-branch extraction over one function body."""

    def __init__(self, module, func, klass):
        self.module = module
        self.func = func
        self.klass = klass
        self.typevars = {}     # var assigned from a type-expr -> msgvar
        if re.search(r"handle|dispatch|event", func.name):
            args = func.args.args
            names = [a.arg for a in args if a.arg != "self"]
            kindp = next((n for n in names if n in _KIND_PARAMS), None)
            if kindp is not None:
                msgp = next((n for n in names if n in _MSG_PARAMS), "")
                self.typevars[kindp] = msgp

    def run(self):
        self._collect_typevars()
        self._scan(self.func.body)

    def _collect_typevars(self):
        for node in ast.walk(self.func):
            if isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                tgt = _type_expr_target(node.value, {})
                if tgt is not None:
                    self.typevars[node.targets[0].id] = tgt

    def _classify(self, test):
        """(eq, neq): [(kind, msgvar)] for every discriminator compare
        anywhere in ``test`` (BoolOp/Not included)."""
        eq, neq = [], []
        for cmp_ in [n for n in ast.walk(test)
                     if isinstance(n, ast.Compare)]:
            if len(cmp_.ops) != 1:
                continue
            sides = (cmp_.left, cmp_.comparators[0])
            for expr, other in (sides, sides[::-1]):
                msgvar = _type_expr_target(expr, self.typevars)
                kinds = _const_strs(other)
                if msgvar is None or kinds is None:
                    continue
                op = cmp_.ops[0]
                self.module.handled.update(kinds)
                if isinstance(op, (ast.Eq, ast.In)):
                    eq.extend((k, msgvar) for k in kinds)
                elif isinstance(op, (ast.NotEq, ast.NotIn)):
                    neq.extend((k, msgvar) for k in kinds)
                break
        return eq, neq

    def _scan(self, stmts):
        for i, stmt in enumerate(stmts):
            if isinstance(stmt, ast.If):
                eq, neq = self._classify(stmt.test)
                for kind, msgvar in eq:
                    self.module.branches.append(_Branch(
                        kind, stmt.body, msgvar, self.klass,
                        self.func.name, stmt.lineno))
                if neq and _terminates(stmt.body):
                    # guard idiom: `if msg.get("type") != "register":
                    # ... return` — the REST of the block is the branch
                    for kind, msgvar in neq:
                        self.module.branches.append(_Branch(
                            kind, stmts[i + 1:], msgvar, self.klass,
                            self.func.name, stmt.lineno))
                self._scan(stmt.body)
                self._scan(stmt.orelse)
            elif isinstance(stmt, (ast.For, ast.While, ast.With)):
                self._scan(stmt.body)
                self._scan(getattr(stmt, "orelse", []) or [])
            elif isinstance(stmt, ast.Try):
                self._scan(stmt.body)
                for h in stmt.handlers:
                    self._scan(h.body)
                self._scan(stmt.orelse)
                self._scan(stmt.finalbody)
            # nested defs are scanned as their own functions


class _ModuleAudit(object):
    """All VW9xx extraction + local rules over one parsed file."""

    def __init__(self, path, tree, source):
        self.path = path
        self.tree = tree
        self.lines = source.splitlines()
        self.findings = []
        self.sites = []        # [_MsgSite]
        self.handled = set()   # kinds with any handler/compare/default
        self.branches = []     # [_Branch]
        self.classes = {}      # name -> _ClassInfo

    # -- suppression (the VT8xx contract) -----------------------------
    def _suppressed(self, rule, lineno):
        def marked(ln):
            if not 1 <= ln <= len(self.lines):
                return False
            m = _SUPPRESS_RE.search(self.lines[ln - 1])
            return bool(m and rule in re.split(r"\s*,\s*",
                                               m.group(1)))
        if marked(lineno):
            return True
        ln = lineno - 1
        while 1 <= ln <= len(self.lines) \
                and self.lines[ln - 1].lstrip().startswith("#"):
            if marked(ln):
                return True
            ln -= 1
        return False

    def _emit(self, rule, severity, lineno, message, hint=""):
        if self._suppressed(rule, lineno):
            return
        unit = "%s:%d" % (self.path, lineno)
        self.findings.append(Finding(rule, severity, unit, message,
                                     hint=hint))

    # -- extraction ----------------------------------------------------
    def extract(self):
        self._extract_classes()
        self._extract_sites()
        self._extract_handlers()
        self._extract_get_defaults()

    def _extract_classes(self):
        for cls in [n for n in ast.walk(self.tree)
                    if isinstance(n, ast.ClassDef)]:
            info = _ClassInfo(cls.name)
            for node in cls.body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    info.methods[node.name] = node
            init = info.methods.get("__init__")
            for sub in ast.walk(init) if init else ():
                if not (isinstance(sub, ast.Assign)
                        and len(sub.targets) == 1):
                    continue
                attr = self._self_attr(sub.targets[0])
                if attr is None:
                    continue
                ctor = _dotted(sub.value.func) \
                    if isinstance(sub.value, ast.Call) else None
                if "fence" in attr.lower() or \
                        (ctor and "fence" in ctor.lower()):
                    info.fence_attr = attr
            self.classes[cls.name] = info

    @staticmethod
    def _self_attr(node):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return node.attr
        return None

    def _site_of(self, d, lineno):
        """Dict literal -> _MsgSite (or None): constant "type" value,
        or constant "kind"/"cmd" when flowing straight into a send."""
        fields, open_, kind, disc = [], False, None, None
        for k, v in zip(d.keys, d.values):
            name = _const_str(k) if k is not None else None
            if name is None:
                open_ = True
                continue
            fields.append(name)
            if name in _DISCRIMINATORS and disc is None:
                s = _const_str(v)
                if s is not None:
                    kind, disc = s, name
        if kind is None:
            return None
        site = _MsgSite(kind, fields, open_, lineno)
        site.disc = disc
        return site

    def _extract_sites(self):
        # direct send-helper args qualify for any discriminator; a
        # bare literal qualifies only on "type" (the protocol's key)
        send_args = set()
        for call in [n for n in ast.walk(self.tree)
                     if isinstance(n, ast.Call)]:
            name = _dotted(call.func) or ""
            if name.rsplit(".", 1)[-1] in _SEND_TAILS:
                for a in call.args:
                    send_args.add(id(a))
        sites_by_var = {}
        for fn in [n for n in ast.walk(self.tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]:
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name) and \
                        isinstance(node.value, ast.Dict):
                    site = self._site_of(node.value, node.lineno)
                    if site is not None:
                        sites_by_var[(fn, node.targets[0].id)] = site
                # `spec["x"] = ...` after the literal adds a field
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Subscript) and \
                        isinstance(node.targets[0].value, ast.Name):
                    key = _const_str(node.targets[0].slice)
                    site = sites_by_var.get(
                        (fn, node.targets[0].value.id))
                    if site is not None and key is not None:
                        site.fields.add(key)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Dict):
                continue
            site = self._site_of(node, node.lineno)
            if site is None:
                continue
            if site.disc == "type" or id(node) in send_args:
                self.sites.append(site)

    def _extract_handlers(self):
        for fn in [n for n in ast.walk(self.tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]:
            klass = None
            for cls in self.classes.values():
                if cls.methods.get(fn.name) is fn:
                    klass = cls
                    break
            _FuncScan(self, fn, klass).run()

    def _extract_get_defaults(self):
        # msg.get("type", "garbage"): the default is a handled kind
        # (the inbox pump's classification of torn lines)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "get" and len(node.args) == 2 \
                    and _const_str(node.args[0]) in _DISCRIMINATORS:
                s = _const_str(node.args[1])
                if s is not None:
                    self.handled.add(s)

    # -- branch closure ------------------------------------------------
    def _closure_scopes(self, branch):
        """[(stmts, msgvar)]: the branch body plus every same-class
        method the message variable is passed into (depth <= 3)."""
        scopes, seen = [], set()

        def expand(stmts, msgvar, klass, depth):
            scopes.append((stmts, msgvar))
            if depth >= 3 or klass is None or not msgvar:
                return
            for node in ast.walk(ast.Module(body=list(stmts),
                                            type_ignores=[])):
                if not isinstance(node, ast.Call):
                    continue
                name = _dotted(node.func) or ""
                parts = name.split(".")
                if len(parts) != 2 or parts[0] != "self":
                    continue
                callee = klass.methods.get(parts[1])
                if callee is None:
                    continue
                for pos, arg in enumerate(node.args):
                    if isinstance(arg, ast.Name) and \
                            arg.id == msgvar:
                        params = [a.arg for a in callee.args.args
                                  if a.arg != "self"]
                        if pos < len(params) and \
                                (parts[1], params[pos]) not in seen:
                            seen.add((parts[1], params[pos]))
                            expand(callee.body, params[pos], klass,
                                   depth + 1)
        expand(branch.body, branch.msgvar, branch.klass, 0)
        return scopes

    @staticmethod
    def _walk_scopes(scopes):
        for stmts, msgvar in scopes:
            for stmt in stmts:
                for node in ast.walk(stmt):
                    yield node, msgvar

    # -- rules ---------------------------------------------------------
    def check_branches(self, senders, handled):
        """VW901/VW902/VW903 over this module's handler branches, with
        the cross-module sender/handled registries."""
        for br in self.branches:
            scopes = self._closure_scopes(br)
            self._vw901(br, scopes, senders)
            self._vw902(br, scopes)
            self._vw903(br, scopes)

    def _vw901(self, br, scopes, senders):
        sites = senders.get(br.kind)
        if not sites or any(s.open for s in sites) or not br.msgvar:
            return
        fields = set().union(*(s.fields for s in sites))
        flagged = set()
        for node, msgvar in self._walk_scopes(scopes):
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, ast.Load) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == msgvar:
                f = _const_str(node.slice)
                if f is not None and f not in fields \
                        and f not in flagged:
                    flagged.add(f)
                    self._emit(
                        "VW901", ERROR, node.lineno,
                        "handler for %r subscripts %s[%r], a field no "
                        "sender of that kind sets (senders set: %s)"
                        % (br.kind, msgvar, f,
                           ", ".join(sorted(fields))),
                        hint="set the field at every sender, or read "
                             "it with .get() and handle the miss")

    def _vw902(self, br, scopes):
        if not _REQUEST_RE.search(br.kind):
            return
        for node, _mv in self._walk_scopes(scopes):
            if isinstance(node, ast.Call):
                name = _dotted(node.func) or ""
                if name.rsplit(".", 1)[-1] in _SEND_TAILS:
                    return
        self._emit(
            "VW902", ERROR, br.lineno,
            "request-shaped kind %r is handled without ever sending a "
            "response — the requester waits forever" % br.kind,
            hint="send an ack/response message from the handler (or "
                 "rename the kind if it is fire-and-forget)")

    def _vw903(self, br, scopes):
        if br.klass is None or br.klass.fence_attr is None \
                or not br.msgvar:
            return
        fence = "self." + br.klass.fence_attr
        reads_inc = mutates = consults = False
        for node, msgvar in self._walk_scopes(scopes):
            if self._is_incarnation_read(node, msgvar):
                reads_inc = True
            d = _dotted(node) if isinstance(node, ast.Attribute) \
                else None
            if d and (d == fence or d.startswith(fence + ".")):
                consults = True
            if isinstance(node, ast.Compare):
                for side in [node.left] + node.comparators:
                    for sub in ast.walk(side):
                        if self._is_incarnation_read(sub, None):
                            consults = True
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets \
                    if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    for el in ast.walk(t):
                        if self._self_attr(el) or (
                                isinstance(el, ast.Subscript)
                                and self._self_attr(el.value)):
                            mutates = True
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS and \
                    self._self_attr(node.func.value):
                mutates = True
        if reads_inc and mutates and not consults:
            self._emit(
                "VW903", ERROR, br.lineno,
                "%s handler for %r reads the message's incarnation and "
                "mutates state without consulting the incarnation "
                "fence — a zombie from a fenced life is re-admitted"
                % (br.klass.name, br.kind),
                hint="admit through the fence (fence.admit / compare "
                     "against the current incarnation) before "
                     "touching state")

    @staticmethod
    def _is_incarnation_read(node, msgvar):
        """``X.get("incarnation")`` or ``X["incarnation"]`` — when
        ``msgvar`` is given, only on that name."""
        def base_ok(base):
            return msgvar is None or (
                isinstance(base, ast.Name) and base.id == msgvar)
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "get" and node.args and \
                _const_str(node.args[0]) == "incarnation":
            return base_ok(node.func.value)
        if isinstance(node, ast.Subscript) and \
                _const_str(node.slice) == "incarnation":
            return base_ok(node.value)
        return False

    # -- module-local rules -------------------------------------------
    def _guard_regions(self, guard_tails):
        regions = []
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Try):
                continue
            ok = False
            for h in node.handlers:
                if h.type is None:
                    ok = True
                    continue
                types = h.type.elts \
                    if isinstance(h.type, ast.Tuple) else [h.type]
                for t in types:
                    d = _dotted(t) or ""
                    if d.rsplit(".", 1)[-1] in guard_tails:
                        ok = True
            if ok and node.body:
                end = max(getattr(s, "end_lineno", s.lineno) or
                          s.lineno for s in node.body)
                regions.append((node.body[0].lineno, end))
        return regions

    @staticmethod
    def _in_regions(lineno, regions):
        return any(a <= lineno <= b for a, b in regions)

    def check_sockets(self):
        regions = self._guard_regions(_SOCKET_GUARDS)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func) or ""
            tail = name.rsplit(".", 1)[-1]
            if tail == "settimeout" and len(node.args) == 1 and \
                    isinstance(node.args[0], ast.Constant) and \
                    node.args[0].value is None:
                self._emit(
                    "VW904", WARNING, node.lineno,
                    "settimeout(None): the read blocks forever on a "
                    "silent peer",
                    hint="bound the read (or keep the unbounded read "
                         "with a lint-ok rationale for why EOF is the "
                         "liveness signal)")
            elif tail == "create_connection":
                has_timeout = len(node.args) >= 2 or any(
                    kw.arg == "timeout" for kw in node.keywords)
                if not has_timeout:
                    self._emit(
                        "VW904", WARNING, node.lineno,
                        "socket.create_connection without a timeout: "
                        "a black-holed master address hangs the "
                        "connect forever",
                        hint="pass timeout=...")
            elif tail == "accept" and not node.args and \
                    not self._in_regions(node.lineno, regions):
                self._emit(
                    "VW904", WARNING, node.lineno,
                    "accept() outside try/except OSError: closing the "
                    "listener from the stop path raises in the accept "
                    "thread instead of unblocking it",
                    hint="wrap the accept in try/except OSError: "
                         "return (the close-unblocks idiom)")

    def check_json_loads(self):
        regions = self._guard_regions(_JSON_GUARDS)
        funcs = {}    # name -> FunctionDef (innermost wins is fine)
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                funcs[node.name] = node
        for fn in funcs.values():
            assigns = {}
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name):
                    assigns[node.targets[0].id] = node.value
            params = {a.arg for a in fn.args.args}
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and (_dotted(node.func) or "")
                        .rsplit(".", 1)[-1] == "loads"
                        and "json" in (_dotted(node.func) or "")
                        and node.args):
                    continue
                if not self._wire_derived(node.args[0], assigns,
                                          params):
                    continue
                if self._in_regions(node.lineno, regions):
                    continue
                if self._callers_guarded(fn.name, regions):
                    continue
                self._emit(
                    "VW905", ERROR, node.lineno,
                    "json.loads of wire input with no ValueError "
                    "guard here or around its callers — one torn "
                    "line kills the owning thread",
                    hint="wrap in try/except ValueError and classify "
                         "the garbage (the _Conn.recv idiom)")

    def _wire_derived(self, expr, assigns, params):
        def expr_is_wire(e, depth=0):
            for node in ast.walk(e):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute):
                    tail = node.func.attr
                    chain = _dotted(node.func) or ""
                    roots = chain.lower().split(".")
                    if tail in _WIRE_READ_TAILS:
                        return True
                    if tail == "read" and any(
                            r in roots for r in _WIRE_READ_ROOTS):
                        return True
                if isinstance(node, ast.Name) and depth < 2:
                    if node.id in params and \
                            node.id in _WIRE_PARAMS:
                        return True
                    if node.id in assigns and \
                            assigns[node.id] is not e and \
                            expr_is_wire(assigns[node.id], depth + 1):
                        return True
            return False
        return expr_is_wire(expr)

    def _callers_guarded(self, fname, regions):
        sites = []
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and \
                    (_dotted(node.func) or "") \
                    .rsplit(".", 1)[-1] == fname:
                sites.append(node.lineno)
        return bool(sites) and all(
            self._in_regions(ln, regions) for ln in sites)


def _audit_module(path, root=None):
    with open(path) as fh:
        source = fh.read()
    rel = os.path.relpath(path, root) if root else path
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        mod = None
        finding = Finding("VW900", ERROR, "%s:%d" % (rel, e.lineno or 0),
                          "file failed to parse: %s" % e)
        return mod, [finding]
    return _ModuleAudit(rel, tree, source), []


def lint_protocol(paths=None, root=None):
    """VW9xx over a file set — default: every ``.py`` under
    ``veles_tpu/services`` (the control plane).  The scanned files form
    ONE protocol universe: a kind sent in one module and handled in
    another is matched across them.  Returns sorted Findings; inline
    ``# lint-ok: VWxxx — reason`` comments suppress accepted sites."""
    if paths is None:
        here = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        base = os.path.join(here, "services")
        root = root or os.path.dirname(here)
        paths = sorted(
            os.path.join(base, f) for f in os.listdir(base)
            if f.endswith(".py"))
    findings, modules = [], []
    for p in paths:
        mod, errs = _audit_module(p, root=root)
        findings.extend(errs)
        if mod is not None:
            mod.extract()
            modules.append(mod)
    handled = set().union(*(m.handled for m in modules)) \
        if modules else set()
    senders = {}
    for m in modules:
        for s in m.sites:
            senders.setdefault(s.kind, []).append(s)
    for m in modules:
        for s in m.sites:
            if s.kind not in handled:
                m._emit(
                    "VW900", ERROR, s.lineno,
                    "message kind %r is constructed here but handled "
                    "nowhere in the scanned tree — the send is a "
                    "silent no-op on the peer" % s.kind,
                    hint="add a handler branch for it (or delete the "
                         "dead send)")
        m.check_branches(senders, handled)
        m.check_sockets()
        m.check_json_loads()
        findings.extend(m.findings)
    return sort_findings(findings)
