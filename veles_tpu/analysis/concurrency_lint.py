"""Concurrency lint for the threaded control plane (VT8xx).

The serving control plane (``services/``) is real threaded Python:
engine loops, HTTP workers, watchdog pumps, signal handlers — PRs 5–15
grew it to the point where the only validation was dynamic (chaos
gates, 250-client storms).  This lint reasons about the *source*: an
AST pass over each module builds a **thread-entry-point map** — every
function a new thread, a signal, or an HTTP worker can enter — closes
it over same-class method calls, and checks the shared state those
entry points touch.  Pure python-on-python analysis: nothing is
imported, nothing runs, no jax involved.

Rule catalog (docs/static_analysis.md):

========  =======  ======================================================
VT800     warning  shared mutable attribute written from >= 2 thread
                   entry points with no common lock held at the writes
VT801     error    lock-order inversion: two locks of one class are
                   nested in opposite orders on different paths —
                   a textbook deadlock waiting for its interleaving
VT802     error    signal handler reaches non-reentrant code: a plain
                   ``threading.Lock``/``Condition`` acquire (or a
                   blocking queue op) inside the handler's call
                   closure — handlers interrupt the main thread
                   mid-bytecode, possibly while it already holds that
                   very lock (the PR 5 flight ring took an RLock for
                   exactly this)
VT803     warning  non-daemon thread started but never joined on any
                   stop path — process exit hangs on it
VT804     warning  raw unbounded ``queue.Queue()`` — a dead consumer
                   accumulates without limit; ``lifecycle
                   .BoundedStream`` exists for exactly this reason
========  =======  ======================================================

**Suppression**: a genuine-but-accepted site carries its rationale
inline — ``# lint-ok: VT804 — terminal queue, bounded by slot count``
on the flagged line (or the line above) suppresses that one rule at
that one site.  A bare ``# lint-ok:`` without a rule id suppresses
nothing: the rationale must name what it accepts.
"""

import ast
import os
import re

from veles_tpu.analysis.findings import (ERROR, WARNING, Finding,
                                         sort_findings)

#: the full VT8xx family, in catalog order
RULES = ("VT800", "VT801", "VT802", "VT803", "VT804")

_SUPPRESS_RE = re.compile(r"#\s*lint-ok:\s*([A-Z]{2}\d{3}(?:\s*,\s*"
                          r"[A-Z]{2}\d{3})*)")

#: constructor names that build a lock-like object, -> kind
_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock",
               "Condition": "condition", "Semaphore": "semaphore",
               "BoundedSemaphore": "semaphore"}

#: attribute-name fragments that mark a lock-like attr even without a
#: visible constructor (built elsewhere / injected)
_LOCKISH = ("lock", "mutex", "cond")


def _dotted(node):
    """``a.b.c`` -> "a.b.c" (None for anything fancier)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _self_attr(node):
    """``self.x`` -> "x" (None otherwise)."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _call_name(call):
    return _dotted(call.func) if isinstance(call, ast.Call) else None


def _is_lock_ctor(call):
    name = _call_name(call)
    if not name:
        return None
    tail = name.rsplit(".", 1)[-1]
    return _LOCK_CTORS.get(tail)


class _MethodInfo(object):
    """Everything VT8xx needs to know about one function body."""

    def __init__(self, name):
        self.name = name
        self.writes = {}          # attr -> [(lineno, frozenset(locks))]
        self.acquires = []        # (lock, lineno, held-before frozenset)
        self.calls = {}           # self-method name -> [(lineno, held)]
        self.lock_pairs = set()   # (outer, inner) nesting order


class _FunctionScanner(ast.NodeVisitor):
    """One pass over a function body, tracking the set of self-locks
    held at each statement (``with self.X:`` scoping)."""

    def __init__(self, info, lock_attrs):
        self.info = info
        self.lock_attrs = lock_attrs
        self.held = ()

    # -- lock scoping -------------------------------------------------
    def visit_With(self, node):
        acquired = []
        for item in node.items:
            expr = item.context_expr
            # `with self.x:` and `with self.x.acquire…` / timeouts
            target = expr
            if isinstance(target, ast.Call):
                target = target.func
            attr = _self_attr(target)
            if attr and (attr in self.lock_attrs
                         or any(k in attr.lower() for k in _LOCKISH)):
                for outer in self.held:
                    self.info.lock_pairs.add((outer, attr))
                self.info.acquires.append(
                    (attr, node.lineno, frozenset(self.held)))
                acquired.append(attr)
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        prev = self.held
        self.held = prev + tuple(a for a in acquired
                                 if a not in prev)
        for stmt in node.body:
            self.visit(stmt)
        self.held = prev

    visit_AsyncWith = visit_With

    # -- shared-state writes ------------------------------------------
    def _note_write(self, target, lineno):
        attr = _self_attr(target)
        if attr is None or attr in self.lock_attrs:
            return
        self.info.writes.setdefault(attr, []).append(
            (lineno, frozenset(self.held)))

    def visit_Assign(self, node):
        for t in node.targets:
            for el in ast.walk(t):
                self._note_write(el, node.lineno)
        self.visit(node.value)

    def visit_AugAssign(self, node):
        self._note_write(node.target, node.lineno)
        self.visit(node.value)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._note_write(node.target, node.lineno)
            self.visit(node.value)

    # -- calls ---------------------------------------------------------
    def visit_Call(self, node):
        name = _dotted(node.func)
        if name and name.startswith("self."):
            parts = name.split(".")
            if len(parts) == 2:          # self.method(...)
                self.info.calls.setdefault(parts[1], []).append(
                    (node.lineno, frozenset(self.held)))
            else:
                # self.attr.method(...): a mutating container call on
                # shared state counts as a write of the attr
                if parts[-1] in ("append", "add", "pop", "popleft",
                                 "appendleft", "remove", "clear",
                                 "update", "extend", "setdefault",
                                 "discard", "insert"):
                    self._note_write(
                        ast.Attribute(value=ast.Name(id="self"),
                                      attr=parts[1]),
                        node.lineno)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):   # nested defs: new held scope
        prev, self.held = self.held, ()
        for stmt in node.body:
            self.visit(stmt)
        self.held = prev

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = lambda self, node: self.visit(node.body)  # noqa: E731


class _ClassModel(object):
    def __init__(self, name):
        self.name = name
        self.methods = {}         # method name -> _MethodInfo
        self.lock_attrs = {}      # attr -> kind ("lock"/"rlock"/...)
        self.entry_points = {}    # method name -> entry kind


def _closure(model, start):
    """All methods of ``model`` reachable from ``start`` through
    same-class calls (including ``start`` itself)."""
    seen, stack = set(), [start]
    while stack:
        m = stack.pop()
        if m in seen or m not in model.methods:
            continue
        seen.add(m)
        stack.extend(model.methods[m].calls)
    return seen


class _ModuleLint(object):
    """All VT8xx rules over one parsed source file."""

    def __init__(self, path, tree, source):
        self.path = path
        self.tree = tree
        self.lines = source.splitlines()
        self.findings = []

    # -- suppression ---------------------------------------------------
    def _suppressed(self, rule, lineno):
        """True when the flagged line, or the contiguous comment block
        directly above it, carries ``# lint-ok: <rule>``."""
        def marked(ln):
            if not 1 <= ln <= len(self.lines):
                return False
            m = _SUPPRESS_RE.search(self.lines[ln - 1])
            return bool(m and rule in re.split(r"\s*,\s*",
                                               m.group(1)))
        if marked(lineno):
            return True
        ln = lineno - 1
        while 1 <= ln <= len(self.lines) \
                and self.lines[ln - 1].lstrip().startswith("#"):
            if marked(ln):
                return True
            ln -= 1
        return False

    def _emit(self, rule, severity, lineno, message, hint=""):
        if self._suppressed(rule, lineno):
            return
        unit = "%s:%d" % (self.path, lineno)
        self.findings.append(Finding(rule, severity, unit, message,
                                     hint=hint))

    # -- whole-module scans -------------------------------------------
    def run(self):
        self._scan_queues()
        self._scan_threads()
        handlers = self._signal_handlers()
        for cls in [n for n in ast.walk(self.tree)
                    if isinstance(n, ast.ClassDef)]:
            self._lint_class(self._model(cls), handlers)
        self._lint_module_handlers(handlers)
        return self.findings

    def _scan_queues(self):
        for node in ast.walk(self.tree):
            name = _call_name(node)
            if not name or name.rsplit(".", 1)[-1] != "Queue" \
                    or not ("queue" in name or name == "Queue"):
                continue
            maxsize = None
            if node.args:
                maxsize = node.args[0]
            for kw in node.keywords:
                if kw.arg == "maxsize":
                    maxsize = kw.value
            bounded = maxsize is not None and not (
                isinstance(maxsize, ast.Constant)
                and not maxsize.value)
            if not bounded:
                self._emit(
                    "VT804", WARNING, node.lineno,
                    "raw unbounded queue.Queue(): a stalled consumer "
                    "accumulates producer memory without limit",
                    hint="give it a maxsize, or use lifecycle"
                         ".BoundedStream (bounded, never blocks the "
                         "engine thread, terminal always delivered)")

    def _scan_threads(self):
        src = "\n".join(self.lines)
        for node in ast.walk(self.tree):
            name = _call_name(node)
            if not name or name.rsplit(".", 1)[-1] != "Thread":
                continue
            daemon = False
            for kw in node.keywords:
                if kw.arg == "daemon" \
                        and isinstance(kw.value, ast.Constant):
                    daemon = bool(kw.value.value)
            if daemon:
                continue
            # thread object bound to a name/attr that later gets
            # `.daemon = True` or `.join(`?  textual check is enough —
            # the binding styles in services/ are all direct
            if re.search(r"\.daemon\s*=\s*True|\.setDaemon\(True\)"
                         r"|\.join\(", src):
                # conservatively accept if the module joins or
                # daemonizes ANY thread — refine per-name below when
                # the target is a self attr
                parent_ok = True
            else:
                parent_ok = False
            if not parent_ok:
                self._emit(
                    "VT803", WARNING, node.lineno,
                    "non-daemon thread started and never joined "
                    "anywhere in this module — process exit hangs "
                    "on it",
                    hint="daemon=True for pumps whose death is "
                         "harmless, or join it on the stop path")

    def _signal_handlers(self):
        """(handler name, lineno) for every signal.signal(...)
        registration whose handler is a plain name, self-method or
        local function."""
        out = []
        for node in ast.walk(self.tree):
            if _call_name(node) in ("signal.signal",) \
                    and len(node.args) >= 2:
                h = node.args[1]
                hname = _dotted(h)
                if hname:
                    out.append((hname.split(".")[-1], node.lineno))
        return out

    # -- per-class -----------------------------------------------------
    def _model(self, cls):
        model = _ClassModel(cls.name)
        for node in cls.body:
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            info = _MethodInfo(node.name)
            # lock attrs first (from __init__ assignments)
            if node.name == "__init__":
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) \
                            and isinstance(sub.value, ast.Call):
                        kind = _is_lock_ctor(sub.value)
                        if kind:
                            for t in sub.targets:
                                attr = _self_attr(t)
                                if attr:
                                    model.lock_attrs[attr] = kind
            model.methods[node.name] = info
        # scan bodies once lock attrs are known
        for node in cls.body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                scanner = _FunctionScanner(model.methods[node.name],
                                           model.lock_attrs)
                for stmt in node.body:
                    scanner.visit(stmt)
        # entry points: Thread(target=self.m), HTTP do_*, signal
        for node in ast.walk(cls):
            name = _call_name(node)
            if name and name.rsplit(".", 1)[-1] == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        t = _self_attr(kw.value)
                        if t:
                            model.entry_points[t] = "thread"
        for mname in model.methods:
            if mname.startswith("do_"):
                model.entry_points[mname] = "http"
        # a method that registers a LOCAL closure as a signal handler:
        # the closure's self-calls were recorded under the method
        # (nested defs share its _MethodInfo), so treating the method
        # as the signal entry point covers everything the handler can
        # reach — a slight over-approximation on the method's own
        # non-handler calls, which install-time code tolerates
        for node in cls.body:
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            local = {n.name for n in ast.walk(node)
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))
                     and n is not node}
            for sub in ast.walk(node):
                if _call_name(sub) == "signal.signal" \
                        and len(sub.args) >= 2 \
                        and isinstance(sub.args[1], ast.Name) \
                        and sub.args[1].id in local:
                    model.entry_points[node.name] = "signal"
        return model

    def _lint_class(self, model, handlers):
        # signal handlers that are methods of this class
        for hname, lineno in handlers:
            if hname in model.methods:
                model.entry_points.setdefault(hname, "signal")

        # VT801 — opposite nesting orders anywhere in the class
        pairs = set()
        for info in model.methods.values():
            pairs |= info.lock_pairs
            # one level of call closure: a method that calls
            # self.m() while holding A inherits m's acquisitions as
            # nested under A
            for callee, sites in info.calls.items():
                cinfo = model.methods.get(callee)
                if cinfo is None:
                    continue
                for _ln, held in sites:
                    for outer in held:
                        for inner, _l, _h in cinfo.acquires:
                            if inner != outer:
                                pairs.add((outer, inner))
        reported = set()
        for a, b in sorted(pairs):
            if (b, a) in pairs and (b, a) not in reported:
                reported.add((a, b))
                lineno = min(
                    [ln for info in model.methods.values()
                     for at, ln, _h in info.acquires
                     if at in (a, b)] or [1])
                self._emit(
                    "VT801", ERROR, lineno,
                    "%s: locks %r and %r are nested in OPPOSITE "
                    "orders on different paths — a deadlock waiting "
                    "for its interleaving" % (model.name, a, b),
                    hint="pick one global order and take both locks "
                         "in it everywhere (or merge them)")

        # VT802 — signal handler closure reaches non-reentrant code
        for hname, kind in model.entry_points.items():
            if kind != "signal":
                continue
            for m in sorted(_closure(model, hname)):
                info = model.methods[m]
                for attr, lineno, _held in info.acquires:
                    if model.lock_attrs.get(attr, "lock") in (
                            "lock", "condition", "semaphore"):
                        self._emit(
                            "VT802", ERROR, lineno,
                            "%s.%s acquires non-reentrant %r inside "
                            "the %s signal handler's call closure — "
                            "the handler interrupts the main thread, "
                            "possibly while it already holds that "
                            "lock" % (model.name, m, attr, hname),
                            hint="handlers should only set flags / "
                                 "write a self-pipe; do the work on "
                                 "a thread (an RLock only helps "
                                 "same-thread re-entry, not "
                                 "cross-thread waits)")

        # VT800 — attr written from >= 2 entry points, no common lock
        if len(model.entry_points) < 2:
            return
        writers = {}    # attr -> {entry: [lock sets]}
        for entry in model.entry_points:
            for m in _closure(model, entry):
                info = model.methods[m]
                if m == "__init__":
                    continue
                for attr, sites in info.writes.items():
                    slot = writers.setdefault(attr, {})
                    slot.setdefault(entry, []).extend(
                        locks for _ln, locks in sites)
        for attr, by_entry in sorted(writers.items()):
            if len(by_entry) < 2:
                continue
            all_sets = [s for sets in by_entry.values() for s in sets]
            common = frozenset.intersection(*all_sets) \
                if all_sets else frozenset()
            if common:
                continue
            linenos = [ln for e in by_entry
                       for m in _closure(model, e)
                       for ln, _s in
                       model.methods[m].writes.get(attr, [])]
            lineno = min(linenos) if linenos else 1
            self._emit(
                "VT800", WARNING, lineno,
                "%s.%s is written from %d thread entry points (%s) "
                "with no common lock held at the writes"
                % (model.name, attr, len(by_entry),
                   ", ".join("%s[%s]" % (e, model.entry_points[e])
                             for e in sorted(by_entry))),
                hint="guard every write with one lock, or make the "
                     "attribute single-writer and publish through "
                     "an immutable snapshot")

    def _lint_module_handlers(self, handlers):
        """VT802 for module-level handler functions (not methods)."""
        funcs = {n.name: n for n in self.tree.body
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))}
        for hname, _lineno in handlers:
            fn = funcs.get(hname)
            if fn is None:
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.With):
                    for item in node.items:
                        d = _dotted(item.context_expr) or ""
                        if any(k in d.lower() for k in _LOCKISH):
                            self._emit(
                                "VT802", ERROR, node.lineno,
                                "signal handler %r acquires lock-like "
                                "%r — handlers must not block on "
                                "locks" % (hname, d),
                                hint="set a flag / write a self-pipe "
                                     "and handle it on a thread")


def lint_module(path, root=None):
    """VT8xx findings for one source file (unit paths relative to
    ``root`` when given)."""
    with open(path) as fh:
        source = fh.read()
    rel = os.path.relpath(path, root) if root else path
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding("VT800", ERROR, "%s:%d" % (rel, e.lineno or 0),
                        "file failed to parse: %s" % e)]
    return _ModuleLint(rel, tree, source).run()


def lint_concurrency(paths=None, root=None):
    """VT8xx over a file set — default: every ``.py`` under
    ``veles_tpu/services`` (the threaded control plane).  Returns
    sorted Findings; inline ``# lint-ok: VTxxx — reason`` comments
    suppress individual accepted sites."""
    if paths is None:
        here = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        base = os.path.join(here, "services")
        root = root or os.path.dirname(here)
        paths = sorted(
            os.path.join(base, f) for f in os.listdir(base)
            if f.endswith(".py"))
    findings = []
    for p in paths:
        findings.extend(lint_module(p, root=root))
    return sort_findings(findings)
