"""Config & telemetry contract audit (VC95x) + reference generator.

``root.common`` is an auto-vivifying tree: reading a key nobody ever
declared silently returns an empty node, so a typo'd path is not an
error — it is a default, forever.  The flight-recorder and metrics
namespaces are stringly-typed the same way: a chaos gate asserting an
event the code renamed passes vacuously (it asserts "count == 0" by
accident).  This audit builds a whole-tree registry from source (pure
AST — nothing is imported, nothing runs) and lints the contract:

* **declared** keys: the ``root.common.update({...})`` defaults block
  in ``veles_tpu/config.py`` (declaration is the documentation home —
  ``docs/config_reference.md`` is generated from it, see
  :func:`build_reference`);
* **read** sites: attribute chains (``root.common.serve.weights``),
  ``node.get("key", default)`` with inline default, whole-node reads
  (``root.common.get("pod")``), per-scope aliases (``serve_cfg =
  _root.common.serve``), and local knob helpers (``def knob(value,
  key, default): return root.common.pod.get(key, default)``) resolved
  at their call sites; a ``.get`` with a non-constant key marks the
  node dynamically read;
* **runtime-threaded** writes: assignment statements and the
  ``"root.common.pod.size=%d"`` config-list strings the master threads
  into workers;
* **emitted** flight events (``flight.record("pod.fence", ...)`` and
  ``kind="serve.deadline"`` keyword sites) and ``veles_*`` metrics;
* **referenced** event/metric names in tests/, tools/ and docs/.

Rule catalog (docs/static_analysis.md):

========  =======  ======================================================
VC950     error    undeclared key read in exactly one place whose
                   dotted path is edit-distance 1 from a declared (or
                   multiply-read) key — the silent-default typo class
VC951     warning  dead knob: declared in config.py (or documented in
                   docs/) but read by no code
VC952     error    one key, conflicting constant defaults: two read
                   sites disagree, or an inline default contradicts
                   the declared default (which silently wins)
VC953     warning  knob read by code but never declared in config.py —
                   invisible to docs/config_reference.md and to every
                   other reader
VC954     error    test/tool references a flight event or metric
                   nothing emits (a gate asserting a renamed event
                   passes vacuously); **warning** for the converse —
                   an emitted dotted event / metric on no test, tool
                   or docs surface
========  =======  ======================================================

**Suppression**: ``# lint-ok: VC954 — reason`` on the flagged line (or
the contiguous comment block above it) in whichever file the finding
points at — same contract as VT8xx/VW9xx.
"""

import ast
import os
import re

from veles_tpu.analysis.findings import (ERROR, WARNING, Finding,
                                         sort_findings)

#: the full VC95x family, in catalog order
RULES = ("VC950", "VC951", "VC952", "VC953", "VC954")

_SUPPRESS_RE = re.compile(r"#\s*lint-ok:\s*([A-Z]{2}\d{3}(?:\s*,\s*"
                          r"[A-Z]{2}\d{3})*)")

#: sentinel defaults for read sites
_MISSING = "<none>"      # bare attr-chain read: vivifies, no default
_DYNAMIC = "<dynamic>"   # non-constant default expression

_CONFIG_ROOTS = ("root", "_root")
_WRITE_STR_RE = re.compile(r"root\.common\.([A-Za-z_][\w.]*)\s*=")
_DOC_KEY_RE = re.compile(r"root\.common\.([A-Za-z_][\w.]*[\w])")
_EVENT_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$")
_METRIC_RE = re.compile(r"^veles_[a-z0-9_]+$")
_FILE_EXTS = ("json", "jsonl", "py", "md", "txt", "log", "csv", "html",
              "yaml", "yml", "gz", "zip", "pkl", "npz", "npy", "pb",
              "ckpt", "png", "svg", "db", "sock", "mdb", "lst", "h5",
              "hdf5", "wav")
_METRIC_TAILS = ("gauge", "counter", "histogram")
_NODE_TAILS = ("as_dict", "print_", "keys", "items", "values")


def _dotted(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def _const_str(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _default_repr(node):
    if node is None:
        return _MISSING
    if isinstance(node, ast.Constant):
        return repr(node.value)
    return _DYNAMIC


def _edit_distance(a, b):
    """Plain Levenshtein — the near-miss metric for VC950."""
    if abs(len(a) - len(b)) > 1:
        return 2                     # capped: only 0/1 matter
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(prev[j] + 1, cur[j - 1] + 1,
                           prev[j - 1] + (ca != cb)))
        prev = cur
    return prev[-1]


class _Site(object):
    def __init__(self, path, lineno, default):
        self.path = path
        self.lineno = lineno
        self.default = default


class Registry(object):
    """Everything the VC95x rules and the reference generator need."""

    def __init__(self):
        self.reads = {}          # key -> [_Site]
        self.node_reads = set()  # node paths read whole
        self.dynamic_nodes = set()   # node paths read with var keys
        self.declared = {}       # key -> default repr
        self.declared_lines = {}     # key -> config.py lineno
        self.declared_nodes = set()
        self.writes = {}         # key -> [(file, lineno)]
        self.doc_keys = {}       # key -> [(docfile, lineno)]
        self.events = {}         # emitted name -> [(file, lineno)]
        self.event_prefixes = set()  # constant prefixes of dynamic
        self.metrics = {}        # emitted metric -> [(file, lineno)]
        self.metric_prefixes = set()
        self.refs = {}           # referenced name -> [(file, lineno)]
        self.doc_tokens = set()  # event/metric-ish tokens in docs

    # -- derived -------------------------------------------------------
    def covered_by_node(self, key):
        """True when a whole-node or dynamic read covers ``key``."""
        parts = key.split(".")
        for i in range(len(parts)):
            prefix = ".".join(parts[:i + 1])
            if prefix in self.node_reads or \
                    prefix in self.dynamic_nodes:
                return True
        return False

    def is_read(self, key):
        if key in self.reads or self.covered_by_node(key):
            return True
        # a computed node declared as one leaf (`"dirs":
        # _default_dirs()`) is read through its children
        prefix = key + "."
        return any(k.startswith(prefix) for k in self.reads) or \
            any(n.startswith(prefix) or n == key
                for n in self.node_reads | self.dynamic_nodes)

    def declared_ancestor(self, key):
        """A strict ancestor of ``key`` declared as a LEAF (a computed
        dict whose children the AST cannot see)."""
        parts = key.split(".")
        return any(".".join(parts[:i]) in self.declared
                   for i in range(1, len(parts)))

    def config_key_like(self, token):
        """``token`` collides with the config-key namespace (so it is
        not an event reference)."""
        return (token in self.declared or token in self.reads
                or token in self.writes
                or token in self.declared_nodes
                or any(k.startswith(token + ".")
                       for k in self.declared))


class _CodeScan(ast.NodeVisitor):
    """One module: config reads/writes + event/metric emits."""

    def __init__(self, reg, relpath):
        self.reg = reg
        self.relpath = relpath
        self.scopes = [{}]       # alias name -> config path tuple
        self.helpers = [{}]      # helper name -> (path, key_i, dflt_i)
        self.wrappers = {}       # flight-wrapper name -> kind arg index

    def prescan_wrappers(self, tree):
        """Functions that forward a parameter into ``flight.record``
        (tuner's ``_telemetry``) — their call sites name the events."""
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            params = [a.arg for a in fn.args.args]
            if params and params[0] in ("self", "cls"):
                params = params[1:]
            for call in ast.walk(fn):
                if not (isinstance(call, ast.Call) and call.args
                        and isinstance(call.args[0], ast.Name)
                        and call.args[0].id in params):
                    continue
                chain = ".".join(_dotted(call.func) or [])
                if chain.rsplit(".", 1)[-1] == "record" \
                        and "flight" in chain.lower():
                    self.wrappers[fn.name] = \
                        params.index(call.args[0].id)

    # -- chain resolution ---------------------------------------------
    def _resolve(self, node):
        """Config path (tuple, may be empty == the common root) for an
        attribute chain / aliased name, else None."""
        parts = _dotted(node)
        if parts is None:
            if isinstance(node, ast.BoolOp):    # (cfg or {}).get(...)
                for v in node.values:
                    r = self._resolve(v)
                    if r is not None:
                        return r
            return None
        for i in range(len(parts), 0, -1):
            head = parts[:i]
            if len(head) >= 2 and head[0] in _CONFIG_ROOTS \
                    and head[1] == "common":
                return tuple(parts[2:])
            if len(head) >= 3 and head[1] == "root" \
                    and head[2] == "common":    # config.root.common
                return tuple(parts[3:])
            if i == 1:
                for scope in reversed(self.scopes):
                    if parts[0] in scope:
                        return scope[parts[0]] + tuple(parts[1:])
        return None

    def _read(self, path, lineno, default):
        if not path:
            return
        self.reg.reads.setdefault(".".join(path), []).append(
            _Site(self.relpath, lineno, default))

    # -- scoping -------------------------------------------------------
    def visit_FunctionDef(self, node):
        self.scopes.append({})
        self.helpers.append({})
        self._register_helpers(node)
        self.generic_visit(node)
        self.scopes.pop()
        self.helpers.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _register_helpers(self, fn):
        """``def knob(value, key, default): ... return
        root.common.pod.get(key, default)`` -> resolvable call sites."""
        for sub in fn.body:
            if not isinstance(sub, ast.FunctionDef):
                continue
            params = [a.arg for a in sub.args.args]
            for ret in [n for n in ast.walk(sub)
                        if isinstance(n, ast.Return)]:
                call = ret.value
                if not (isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Attribute)
                        and call.func.attr == "get"
                        and len(call.args) == 2
                        and isinstance(call.args[0], ast.Name)
                        and call.args[0].id in params):
                    continue
                base = self._resolve(call.func.value)
                if base is None:
                    continue
                key_i = params.index(call.args[0].id)
                dflt_i = params.index(call.args[1].id) \
                    if isinstance(call.args[1], ast.Name) \
                    and call.args[1].id in params else None
                self.helpers[-1][sub.name] = (base, key_i, dflt_i)

    # -- reads / writes ------------------------------------------------
    def visit_Assign(self, node):
        for t in node.targets:
            path = self._resolve(t) if isinstance(t, ast.Attribute) \
                else None
            if path:
                self.reg.writes.setdefault(".".join(path), []).append(
                    (self.relpath, node.lineno))
        if len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            path = self._resolve(node.value)
            if path is not None:
                self.scopes[-1][node.targets[0].id] = path
                if path:
                    self.reg.node_reads.add(".".join(path))
                self.visit(node.value)   # chains under the alias value
                return
        self.visit(node.value)

    def visit_Call(self, node):
        func = node.func
        if isinstance(func, ast.Attribute):
            base = self._resolve(func.value)
            if base is not None:
                if func.attr == "get" and node.args:
                    key = _const_str(node.args[0])
                    dflt = node.args[1] if len(node.args) > 1 else None
                    if key is not None:
                        if not base and key not in self.reg.declared:
                            # root.common.get("node"): a whole-node
                            # presence probe unless the key is a
                            # declared leaf
                            self.reg.node_reads.add(key)
                        else:
                            self._read(base + (key,), node.lineno,
                                       _default_repr(dflt))
                    else:
                        if base:
                            self.reg.dynamic_nodes.add(".".join(base))
                    for a in node.args[1:]:
                        self.visit(a)
                    return
                if func.attr in _NODE_TAILS and base:
                    self.reg.node_reads.add(".".join(base))
                    return
                if func.attr == "update" and base:
                    # runtime re-declaration: the dict keys are writes
                    for a in node.args:
                        if isinstance(a, ast.Dict):
                            for k in a.keys:
                                s = _const_str(k) if k else None
                                if s:
                                    self.reg.writes.setdefault(
                                        ".".join(base + (s,)),
                                        []).append((self.relpath,
                                                    node.lineno))
                    self.generic_visit(node)
                    return
        if isinstance(func, ast.Name):
            # config.get(chain, default) helper / local knob helpers
            if func.id == "get" and node.args:
                path = self._resolve(node.args[0])
                if path:
                    dflt = node.args[1] if len(node.args) > 1 else None
                    self._read(path, node.lineno, _default_repr(dflt))
                    for a in node.args[1:]:
                        self.visit(a)
                    return
            for frame in reversed(self.helpers):
                if func.id in frame:
                    base, key_i, dflt_i = frame[func.id]
                    key = _const_str(node.args[key_i]) \
                        if key_i < len(node.args) else None
                    if key is None:
                        if base:
                            self.reg.dynamic_nodes.add(".".join(base))
                    else:
                        dflt = node.args[dflt_i] \
                            if dflt_i is not None \
                            and dflt_i < len(node.args) else None
                        self._read(base + (key,), node.lineno,
                                   _default_repr(dflt))
                    break
        self._maybe_emit(node)
        self.generic_visit(node)

    def visit_Attribute(self, node):
        path = self._resolve(node)
        if path is not None:
            if path:
                self._read(path, node.lineno, _MISSING)
            return                     # chain consumed whole
        self.generic_visit(node)

    def visit_Constant(self, node):
        # "root.common.pod.size=%d" config-list thread strings
        if isinstance(node.value, str):
            for m in _WRITE_STR_RE.finditer(node.value):
                self.reg.writes.setdefault(m.group(1), []).append(
                    (self.relpath, node.lineno))

    # -- event / metric emits -----------------------------------------
    def _maybe_emit(self, node):
        chain = ".".join(_dotted(node.func) or [])
        tail = chain.rsplit(".", 1)[-1]
        if tail == "record" and "flight" in chain.lower() \
                and node.args:
            self._note_name(self.reg.events, self.reg.event_prefixes,
                            node.args[0], node.lineno)
        idx = self.wrappers.get(tail)
        if idx is not None and len(node.args) > idx:
            self._note_name(self.reg.events, self.reg.event_prefixes,
                            node.args[idx], node.lineno)
        if tail in _METRIC_TAILS and node.args:
            name = _const_str(node.args[0])
            if name is not None and name.startswith("veles_"):
                self.reg.metrics.setdefault(name, []).append(
                    (self.relpath, node.lineno))
            elif name is None:
                pre = self._const_prefix(node.args[0])
                if pre and pre.startswith("veles_"):
                    self.reg.metric_prefixes.add(pre)
        for kw in node.keywords:
            if kw.arg == "kind" or (kw.arg == "name"
                                    and tail == "emit"):
                s = _const_str(kw.value)
                if s is not None and "." in s:
                    self.reg.events.setdefault(s, []).append(
                        (self.relpath, node.lineno))

    def _note_name(self, table, prefixes, arg, lineno):
        if isinstance(arg, ast.IfExp):      # "a.b" if cond else "a.c"
            self._note_name(table, prefixes, arg.body, lineno)
            self._note_name(table, prefixes, arg.orelse, lineno)
            return
        s = _const_str(arg)
        if s is not None:
            table.setdefault(s, []).append((self.relpath, lineno))
            return
        pre = self._const_prefix(arg)
        if pre:
            prefixes.add(pre)

    @staticmethod
    def _const_prefix(arg):
        """Constant left part of ``"pod.%s" % x`` / f-strings /
        ``"pod." + x`` — the dynamic-emit family marker."""
        if isinstance(arg, ast.BinOp):
            s = _const_str(arg.left)
        elif isinstance(arg, ast.JoinedStr) and arg.values:
            s = _const_str(arg.values[0])
        else:
            s = None
        return s.split("%", 1)[0] if s else None


def _flatten_defaults(d, prefix, reg):
    for k, v in zip(d.keys, d.values):
        name = _const_str(k) if k is not None else None
        if name is None:
            continue
        key = prefix + (name,)
        if isinstance(v, ast.Dict):
            reg.declared_nodes.add(".".join(key))
            _flatten_defaults(v, key, reg)
        else:
            reg.declared[".".join(key)] = (
                repr(v.value) if isinstance(v, ast.Constant)
                else "(computed)")
            reg.declared_lines[".".join(key)] = k.lineno


def _scan_declared(config_path, reg):
    with open(config_path) as fh:
        tree = ast.parse(fh.read(), filename=config_path)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "update" and node.args
                and isinstance(node.args[0], ast.Dict)):
            continue
        parts = _dotted(node.func.value)
        if parts and parts[0] in _CONFIG_ROOTS and len(parts) >= 2 \
                and parts[1] == "common":
            _flatten_defaults(node.args[0], tuple(parts[2:]), reg)


def _scan_docs(doc_paths, reg):
    for p, rel in doc_paths:
        with open(p) as fh:
            for lineno, line in enumerate(fh, 1):
                for m in _DOC_KEY_RE.finditer(line):
                    # `root.common.update({...})` / `.get(...)` in
                    # docs are API mentions, not keys
                    key = m.group(1)
                    parts = key.split(".")
                    while parts and parts[-1] in ("update", "get"):
                        parts.pop()
                    if not parts or parts[0] in ("update", "get"):
                        continue
                    reg.doc_keys.setdefault(".".join(parts), []).append(
                        (rel, lineno))
                for tok in re.findall(r"[A-Za-z_][\w.]*", line):
                    if _EVENT_RE.match(tok) or _METRIC_RE.match(tok):
                        reg.doc_tokens.add(tok)


def _scan_refs(test_paths, reg):
    """Event/metric-shaped string constants in tests/tools."""
    for p, rel in test_paths:
        with open(p) as fh:
            src = fh.read()
        try:
            tree = ast.parse(src, filename=p)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            s = _const_str(node)
            if s is None or "/" in s or s.startswith(
                    ("root.", "veles_tpu", "jax.", "numpy.")):
                continue
            if not (_EVENT_RE.match(s) or _METRIC_RE.match(s)):
                continue
            if s.rsplit(".", 1)[-1] in _FILE_EXTS or s.endswith("_"):
                continue
            reg.refs.setdefault(s, []).append((rel, node.lineno))


def _iter_py(base):
    for dirpath, _dirs, files in os.walk(base):
        if "__pycache__" in dirpath:
            continue
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def _default_tree(root=None):
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo = root or os.path.dirname(here)
    config_path = os.path.join(repo, "veles_tpu", "config.py")
    # the analyzers' own docstrings hold rule examples — not contracts
    skip_dir = os.path.join(repo, "veles_tpu", "analysis")
    code, tests, docs = [], [], []
    for sub in ("veles_tpu", "tools", "samples"):
        base = os.path.join(repo, sub)
        if os.path.isdir(base):
            code.extend(
                p for p in _iter_py(base)
                if os.path.abspath(p) != os.path.abspath(config_path)
                and not os.path.abspath(p).startswith(
                    os.path.abspath(skip_dir) + os.sep))
    for sub in ("tests", "tools"):
        base = os.path.join(repo, sub)
        if os.path.isdir(base):
            tests.extend(_iter_py(base))
    docs_dir = os.path.join(repo, "docs")
    if os.path.isdir(docs_dir):
        docs = [os.path.join(docs_dir, f)
                for f in sorted(os.listdir(docs_dir))
                if f.endswith(".md")]
    return repo, code, config_path, docs, tests


def build_registry(code_paths=None, config_path=None, doc_paths=None,
                   test_paths=None, root=None):
    """Whole-tree contract registry.  Defaults: code = ``veles_tpu/``
    (minus ``config.py``) + ``tools/`` + ``samples/``; declarations =
    ``veles_tpu/config.py``; docs = ``docs/*.md``; references =
    ``tests/`` + ``tools/``."""
    repo, dcode, dconfig, ddocs, dtests = _default_tree(root)
    code_paths = dcode if code_paths is None else code_paths
    config_path = dconfig if config_path is None else config_path
    doc_paths = ddocs if doc_paths is None else doc_paths
    test_paths = dtests if test_paths is None else test_paths
    rel = lambda p: os.path.relpath(p, repo).replace(os.sep, "/")  # noqa: E731
    reg = Registry()
    if config_path and os.path.exists(config_path):
        _scan_declared(config_path, reg)
    for p in code_paths:
        with open(p) as fh:
            src = fh.read()
        try:
            tree = ast.parse(src, filename=p)
        except SyntaxError:
            continue          # the VW/VT lints report parse failures
        scan = _CodeScan(reg, rel(p))
        scan.prescan_wrappers(tree)
        scan.visit(tree)
    _scan_docs([(p, rel(p)) for p in doc_paths], reg)
    _scan_refs([(p, rel(p)) for p in test_paths], reg)
    reg.config_rel = rel(config_path) if config_path else "config.py"
    reg.repo = repo
    return reg


class _Suppressor(object):
    """lint-ok lookup over arbitrary files (findings span the tree)."""

    def __init__(self, repo):
        self.repo = repo
        self.cache = {}

    def __call__(self, rule, relpath, lineno):
        lines = self.cache.get(relpath)
        if lines is None:
            try:
                with open(os.path.join(self.repo, relpath)) as fh:
                    lines = fh.read().splitlines()
            except OSError:
                lines = []
            self.cache[relpath] = lines

        def marked(ln):
            if not 1 <= ln <= len(lines):
                return False
            m = _SUPPRESS_RE.search(lines[ln - 1])
            return bool(m and rule in re.split(r"\s*,\s*",
                                               m.group(1)))
        if marked(lineno):
            return True
        ln = lineno - 1
        while 1 <= ln <= len(lines) \
                and lines[ln - 1].lstrip().startswith("#"):
            if marked(ln):
                return True
            ln -= 1
        return False


def lint_config(registry=None, root=None):
    """VC95x findings over the contract registry (built from the repo
    tree when not given).  Returns sorted Findings."""
    reg = registry if registry is not None \
        else build_registry(root=root)
    suppressed = _Suppressor(getattr(reg, "repo", root or "."))
    findings = []

    def emit(rule, severity, relpath, lineno, message, hint=""):
        if suppressed(rule, relpath, lineno):
            return
        findings.append(Finding(rule, severity,
                                "%s:%d" % (relpath, lineno), message,
                                hint=hint))

    known = set(reg.declared) | set(reg.writes) | \
        {k for k, sites in reg.reads.items() if len(sites) > 1}

    # VC950 / VC953 — undeclared reads: typo near-miss vs new knob
    for key in sorted(reg.reads):
        if key in reg.declared or key in reg.writes \
                or key in reg.declared_nodes \
                or reg.covered_by_node(key):
            continue
        sites = reg.reads[key]
        near = None
        if len(sites) == 1:
            near = next((c for c in sorted(known)
                         if c != key
                         and _edit_distance(key, c) <= 1), None)
        s = sites[0]
        if reg.declared_ancestor(key):
            continue      # child of a computed dict (e.g. dirs.*)
        if near is not None:
            emit("VC950", ERROR, s.path, s.lineno,
                 "root.common.%s is read exactly once and is edit-"
                 "distance 1 from %r — the silent-default typo class"
                 % (key, near),
                 hint="fix the path (a misspelled key vivifies an "
                      "empty node and returns the default forever)")
        else:
            emit("VC953", WARNING, s.path, s.lineno,
                 "root.common.%s is read by code but never declared "
                 "in config.py — invisible to docs/config_reference"
                 ".md and to every other reader" % key,
                 hint="declare it (with its default) in the "
                      "config.py defaults block")

    # VC951 — dead knobs: declared/documented but read by nothing
    for key in sorted(reg.declared):
        if reg.is_read(key):
            continue
        emit("VC951", WARNING, reg.config_rel,
             reg.declared_lines.get(key, 1),
             "root.common.%s is declared but no code reads it — a "
             "dead knob (setting it does nothing)" % key,
             hint="delete the declaration, or wire the knob into the "
                  "code that was supposed to honor it")
    for key in sorted(reg.doc_keys):
        if key in reg.declared or key in reg.declared_nodes \
                or key in reg.reads or key in reg.writes \
                or reg.covered_by_node(key) \
                or any(k.startswith(key + ".") for k in reg.declared):
            continue
        f, ln = reg.doc_keys[key][0]
        emit("VC951", WARNING, f, ln,
             "docs mention root.common.%s but the key is neither "
             "declared nor read anywhere — stale documentation" % key,
             hint="update the docs (or declare/wire the knob)")

    # VC952 — conflicting constant defaults for one key
    for key in sorted(reg.reads):
        sites = [s for s in reg.reads[key]
                 if s.default not in (_MISSING, _DYNAMIC)]
        values = {}
        for s in sites:
            values.setdefault(s.default, []).append(s)
        declared = reg.declared.get(key)
        if declared is not None and declared != "(computed)":
            values.setdefault(declared, [])
        if len(values) > 1:
            s = sites[0]
            desc = ", ".join(
                "%s (%s)" % (v,
                             "declared" if not sts else
                             "; ".join("%s:%d" % (x.path, x.lineno)
                                       for x in sts))
                for v, sts in sorted(values.items()))
            emit("VC952", ERROR, s.path, s.lineno,
                 "root.common.%s has conflicting defaults: %s — the "
                 "declared default silently wins over every inline "
                 "one" % (key, desc),
                 hint="unify on the config.py declaration (inline "
                      "defaults must match it exactly)")

    # VC954 — event/metric contract, both directions
    families = {e.split(".", 1)[0] for e in reg.events if "." in e}
    surface = set(reg.refs) | reg.doc_tokens

    def emitted(name):
        if name in reg.events or name in reg.metrics:
            return True
        prefixes = reg.event_prefixes | reg.metric_prefixes
        return any(name.startswith(p) for p in prefixes)

    for name in sorted(reg.refs):
        if emitted(name) or reg.config_key_like(name):
            continue
        if _METRIC_RE.match(name) or (
                "." in name and name.split(".", 1)[0] in families):
            f, ln = reg.refs[name][0]
            emit("VC954", ERROR, f, ln,
                 "references %r, a flight event / metric nothing "
                 "emits — the gate passes vacuously" % name,
                 hint="rename the reference to the emitted name (or "
                      "restore the emit this gate was written for)")
    for name in sorted(reg.events):
        if "." in name and name not in surface:
            f, ln = reg.events[name][0]
            emit("VC954", WARNING, f, ln,
                 "flight event %r is emitted but appears on no test, "
                 "tool or docs surface" % name,
                 hint="regenerate docs/config_reference.md (the "
                      "generated catalog is the blackbox operator "
                      "surface)")
    for name in sorted(reg.metrics):
        if name not in surface:
            f, ln = reg.metrics[name][0]
            emit("VC954", WARNING, f, ln,
                 "metric %r is emitted but appears on no test, tool "
                 "or docs surface" % name,
                 hint="regenerate docs/config_reference.md")
    return sort_findings(findings)


# ---------------------------------------------------------------- docs
def build_reference(registry=None, root=None):
    """``docs/config_reference.md`` content from the registry —
    deterministic (sorted keys, file paths without line numbers) so CI
    can diff the checked-in file against a fresh generation."""
    reg = registry if registry is not None \
        else build_registry(root=root)
    out = []
    w = out.append
    w("# Config & telemetry contract reference")
    w("")
    w("Generated by `veles-tpu-lint --config-audit --format markdown`"
      " — do not edit")
    w("by hand.  The `contract-audit` CI job regenerates it and fails"
      " when this")
    w("file is stale.  Rule catalog: docs/static_analysis.md"
      " (VC95x).")
    w("")
    w("## Config keys (`root.common.*`)")
    w("")
    w("| key | default | read by | docs |")
    w("| --- | --- | --- | --- |")
    keys = sorted(set(reg.declared) | set(reg.reads))
    for key in keys:
        if key in reg.declared_nodes:
            continue
        files = sorted({s.path for s in reg.reads.get(key, ())})
        docs = sorted({f for f, _ln in reg.doc_keys.get(key, ())})
        w("| `%s` | `%s` | %s | %s |"
          % (key, reg.declared.get(key, "—"),
             ", ".join("`%s`" % f for f in files) or "—",
             ", ".join(docs) or "—"))
    w("")
    w("## Runtime-threaded keys")
    w("")
    w("Written by code (config-list threading / live reconfiguration),"
      " read")
    w("through whole-node reads — not knobs a user sets.")
    w("")
    w("| key | written by |")
    w("| --- | --- |")
    for key in sorted(reg.writes):
        files = sorted({f for f, _ln in reg.writes[key]})
        w("| `%s` | %s |"
          % (key, ", ".join("`%s`" % f for f in files)))
    w("")
    w("## Flight events")
    w("")
    w("| event | emitted by |")
    w("| --- | --- |")
    for name in sorted(reg.events):
        files = sorted({f for f, _ln in reg.events[name]})
        w("| `%s` | %s |"
          % (name, ", ".join("`%s`" % f for f in files)))
    for pre in sorted(reg.event_prefixes):
        w("| `%s*` | (dynamic family) |" % pre)
    w("")
    w("## Metrics")
    w("")
    w("| metric | emitted by |")
    w("| --- | --- |")
    for name in sorted(reg.metrics):
        files = sorted({f for f, _ln in reg.metrics[name]})
        w("| `%s` | %s |"
          % (name, ", ".join("`%s`" % f for f in files)))
    for pre in sorted(reg.metric_prefixes):
        w("| `%s*` | (dynamic family) |" % pre)
    w("")
    return "\n".join(out)
