"""Decode-path auditor: static lint of the serving engine's decode tick
and segmented-prefill pass (VD7xx).

The serving hot loop is the continuous batcher's tick — ONE jitted,
state-donated dispatch (``ContinuousBatcher._jit_ticks``) that every
in-flight request's decode shares.  Anything wrong inside it is paid on
every generated token of every request: a stray dense dequant streams
float weights again (the exact bug class PR 14's quantized decode
erased), a lost donation doubles the KV pool in HBM, a host callback
serializes the XLA stream per token, a weak-typed scalar retraces the
tick per distinct value, and a mis-sized paged-pool block retiles every
VMEM copy of the fused kernel.  All of it is statically decidable: the
auditor abstractly traces the batcher's OWN tick body
(``_tick_body()`` — the same function serving jits, so the lint can
never audit a different tick than serving runs) over
``jax.ShapeDtypeStruct`` mirrors of the live state, and never
dispatches a single decode step.

Rule catalog (docs/static_analysis.md):

========  =======  ======================================================
VD700     error    quantized payload dequantized outside a dot: an
                   int8→float convert of payload size in the traced tick
                   whose result does not feed a ``dot_general``
                   (``ops.quant.stray_dequant_sites`` — the PR 14 jaxpr
                   test generalized into a rule)
VD701     error    donation miss on decode carry state: a state leaf
                   (KV pool / block tables / active flags / sample
                   state) is not aliased in the lowered tick — it is
                   re-allocated on every dispatch
VD702     error    host callback or host transfer inside the tick
                   (``debug_callback`` / ``pure_callback`` /
                   ``io_callback`` / infeed / outfeed), or a tick that
                   fails to trace abstractly at all (host state in the
                   trace)
VD703     warning  retrace hazard: a weak-typed python scalar in the
                   tick signature — each distinct value recompiles the
                   tick (the PR 3 compile counters,
                   ``veles_compile_events_total``, count the damage at
                   runtime; this rule catches it before)
VD704     warning  TP collective volume per tick exceeds the tick's
                   KV-read bytes — the decode is ICI-bound, not
                   HBM-bound (bytes priced with ``ops.flops``)
VD705     mirror   paged-pool launch geometry fails the VP6xx audit at
                   the block the engine actually resolved (config >
                   tuner winner > default — the same chain the launch
                   would use); severity mirrors the underlying VP rule
========  =======  ======================================================
"""

import jax
import jax.numpy as jnp

from veles_tpu.analysis.findings import (ERROR, WARNING, Finding,
                                         sort_findings)
from veles_tpu.analysis.staging import _aval_str, iter_primitives

#: the full VD7xx family, in catalog order
RULES = ("VD700", "VD701", "VD702", "VD703", "VD704", "VD705")

#: primitive names that round-trip device -> host mid-tick
_HOST_SYNC_PRIMS = ("outfeed", "infeed")

#: collective kinds priced by VD704 (the sharding auditor's grammar)
_COLLECTIVES = ("all-gather", "all-reduce", "reduce-scatter",
                "all-to-all", "collective-permute")


def _abstract(tree, with_shardings=False):
    """ShapeDtypeStruct mirror of a pytree of arrays.  With
    ``with_shardings`` each leaf that carries a mesh (NamedSharding)
    keeps it, so a lowering sees the same post-SPMD module serving
    would compile — still nothing concrete."""
    def leaf(a):
        if not hasattr(a, "shape"):
            # a python scalar in the tree stays concrete — exactly the
            # weak-type retrace hazard VD703 exists to flag
            return a
        sh = getattr(a, "sharding", None) if with_shardings else None
        if sh is not None and hasattr(sh, "spec"):     # NamedSharding
            return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh)
        return jax.ShapeDtypeStruct(a.shape, a.dtype)
    return jax.tree_util.tree_map(leaf, tree,
                                  is_leaf=lambda x: hasattr(x, "shape"))


def _tick_name(cb):
    gen = cb.gen
    tags = [getattr(gen, "weight_dtype", None) or "bf16"]
    if getattr(cb, "block", None):
        tags.append("paged%s" % ("-q8" if getattr(gen, "cache_dtype",
                                                  None) == "int8"
                                 else ""))
    if getattr(cb, "speculative_k", 0):
        tags.append("spec%d" % cb.speculative_k)
    return "decode[%s]" % ",".join(tags)


def _scan_jaxpr(closed, name, params=None, scheme=None):
    """The three jaxpr-level rules over one traced pass: VD700 (when a
    quantized param tree is given), VD702, VD703."""
    findings = []

    if scheme and params is not None:
        from veles_tpu.ops import quant
        try:
            thr = quant.min_payload_elems(params)
        except ValueError:        # no quantized leaves after all
            thr = None
        if thr:
            for site in quant.stray_dequant_sites(closed, thr):
                findings.append(Finding(
                    "VD700", ERROR, name,
                    "quantized payload dequantized outside a dot: %s "
                    "— XLA hoists the dense float copy out of the "
                    "decode scan and the loop streams floats again"
                    % site,
                    hint="keep the int8/int4 payload narrow into the "
                         "dot (ops.quant int8_matmul / w4a8_matmul "
                         "funnels); dequantize per-row only for "
                         "gathers"))

    seen = set()
    for prim_name, _eqn in iter_primitives(closed.jaxpr):
        if "callback" not in prim_name \
                and prim_name not in _HOST_SYNC_PRIMS:
            continue
        if prim_name in seen:
            continue
        seen.add(prim_name)
        what = ("jax.debug.print/debug.callback"
                if prim_name == "debug_callback" else prim_name)
        findings.append(Finding(
            "VD702", ERROR, name,
            "host callback/transfer inside the decode tick (%s): "
            "every generated token round-trips device -> host and "
            "serializes the XLA stream for the whole pool" % what,
            hint="move host work (logging, metrics, numpy) to the "
                 "engine thread outside the tick; fetch stats from "
                 "the tick's outputs instead"))

    for i, aval in enumerate(closed.in_avals):
        if getattr(aval, "weak_type", False):
            findings.append(Finding(
                "VD703", WARNING, name,
                "tick input leaf %d is weak-typed (%s): a python "
                "scalar leaked into the tick signature — each "
                "distinct value retraces and recompiles the tick "
                "(veles_compile_events_total counts these at "
                "runtime)" % (i, _aval_str(aval)),
                hint="wrap host scalars at admission, e.g. "
                     "jnp.int32(x) / jnp.asarray(x, dtype) — the "
                     "admit bodies already do this for the state "
                     "tuple"))
    return findings


def _kv_leaves(state):
    """The KV-carrying leaves of a batcher state tuple: cache/pool
    tensors are >= 3-D, the token matrix and per-slot vectors are
    not."""
    return [l for l in jax.tree_util.tree_leaves(state)
            if getattr(l, "ndim", 0) >= 3]


def audit_decode_tick(cb, vmem_kib=None, name=None):
    """All VD7xx rules over one batcher's decode tick.

    ``cb`` is a constructed ``ContinuousBatcher`` /
    ``PagedContinuousBatcher`` (construction allocates its zero-filled
    state, exactly like ``--numerics`` allocates parameters); the audit
    itself traces and lowers abstractly — no tick is ever
    dispatched."""
    gen = cb.gen
    name = name or _tick_name(cb)
    findings = []

    state = cb._state()
    abstract = _abstract((gen.params, state, cb._aids))
    try:
        body = cb._tick_body()
        closed = jax.make_jaxpr(body)(*abstract)
    except Exception as e:  # noqa: BLE001 — the failure IS the finding
        findings.append(Finding(
            "VD702", ERROR, name,
            "decode tick failed to trace abstractly: %s: %s — host "
            "state or data-dependent python control flow is inside "
            "the tick" % (type(e).__name__, e),
            hint="the tick must be traceable over ShapeDtypeStructs; "
                 "hoist host decisions to admission"))
        return sort_findings(findings + audit_pool_geometry(
            cb, vmem_kib=vmem_kib, name=name))

    findings.extend(_scan_jaxpr(closed, name, params=gen.params,
                                scheme=getattr(gen, "weight_dtype",
                                               None)))

    # ---- VD701: state donation in the ACTUAL dispatch wrapper.  The
    # engine jits through _jit_ticks (donate_argnums=(1,)); donation
    # materializes as per-arg aliasing markers in the lowered module,
    # one per donated state leaf — count them against the state tree.
    try:
        lowered = cb._jit_ticks(body).lower(*abstract)
        text = lowered.as_text()
    except Exception as e:  # noqa: BLE001 — lowering failed: report, don't crash
        findings.append(Finding(
            "VD702", ERROR, name,
            "decode tick failed to lower: %s: %s"
            % (type(e).__name__, e)))
        text = None
    n_state = len(jax.tree_util.tree_leaves(state))
    if text is not None:
        aliased = text.count("tf.aliasing_output")
        if aliased < n_state:
            findings.append(Finding(
                "VD701", ERROR, name,
                "decode carry state not donated: %d of %d state "
                "leaves alias their outputs in the lowered tick — "
                "the rest (KV pool / caches, active flags, sample "
                "state) are re-allocated on EVERY dispatch, doubling "
                "their HBM while the tick runs"
                % (aliased, n_state),
                hint="dispatch through ContinuousBatcher._jit_ticks "
                     "(donate_argnums=(1,)) and keep state outputs "
                     "aval-identical to their inputs"))

    # ---- VD704: TP collective volume per tick vs KV-read bytes.
    # Only meaningful under a model-axis mesh; the collectives GSPMD
    # actually inserts live in the post-SPMD compiled module
    # (sharding_audit's technique) — compiled, never dispatched.
    mc = getattr(gen, "mesh_cfg", None)
    if mc is not None and getattr(mc, "model_size", 1) > 1 \
            and text is not None:
        from veles_tpu.analysis.sharding_audit import collective_stats
        from veles_tpu.ops.flops import shape_nbytes
        sharded = _abstract((gen.params, state, cb._aids),
                            with_shardings=True)
        try:
            compiled = cb._jit_ticks(body).lower(*sharded).compile()
            stats = collective_stats(compiled.as_text())
        except Exception:  # noqa: BLE001 — collective pricing degrades gracefully
            stats = {}
        coll = sum(stats.get(k, {}).get("bytes", 0)
                   for k in _COLLECTIVES)
        coll //= max(1, cb.ticks_per_dispatch)
        kv = sum(shape_nbytes(l.shape, l.dtype)
                 for l in _kv_leaves(state))
        kv //= max(1, getattr(mc, "model_size", 1))
        if coll and coll > kv:
            counts = {k: stats[k]["count"] for k in stats
                      if k in _COLLECTIVES and stats[k]["count"]}
            findings.append(Finding(
                "VD704", WARNING, name,
                "TP collectives move %.2f MiB/device per tick but the "
                "tick reads at most %.2f MiB/device of KV (%s) — the "
                "decode is ICI-bound, the model axis costs more than "
                "the memory traffic it saves"
                % (coll / 2 ** 20, kv / 2 ** 20,
                   ", ".join("%s x%d" % kv_ for kv_ in
                             sorted(counts.items()))),
                hint="shrink the model axis for serving, shard the KV "
                     "heads on it (gen._cache_constraint), or serve "
                     "replicated and route requests instead"))

    findings.extend(audit_pool_geometry(cb, vmem_kib=vmem_kib,
                                        name=name))
    return sort_findings(findings)


def audit_pool_geometry(cb, vmem_kib=None, name=None):
    """VD705: re-audit the paged-pool launch geometry the engine
    RESOLVED (``PagedContinuousBatcher.block`` — config > tuner winner
    > default, the exact chain ``ops.pallas.paged.preferred_pool_block``
    walks at admission) through the VP6xx kernel rules.  Dense batchers
    and gather-fallback pools launch no kernel — nothing to audit."""
    if not getattr(cb, "fused", False) or getattr(cb, "block",
                                                  None) is None:
        return []
    name = name or _tick_name(cb)
    from veles_tpu.analysis.numerics_audit import audit_kernel_launch
    from veles_tpu.ops.pallas import mosaic_sublane_min
    from veles_tpu.ops.pallas import paged as _paged

    pool_leaves = [l for l in jax.tree_util.tree_leaves(cb._pool)
                   if getattr(l, "ndim", 0) == 4]
    if not pool_leaves:
        return []
    leaf = pool_leaves[0]
    # below the sublane minimum the engine ITSELF falls back to the
    # gather tick on real hardware (mosaic_ok in the batcher init) —
    # interpret mode on CPU CI keeps ``fused`` True, but no Mosaic
    # kernel would ever launch with this block, so there is no
    # geometry to audit
    if cb.block < mosaic_sublane_min(leaf.dtype):
        return []
    hkv, hd = int(leaf.shape[1]), int(leaf.shape[-1])
    g = max(1, int(getattr(cb.gen._blocks[0], "n_heads", hkv)) // hkv)
    dtype = leaf.dtype
    launches = _paged.audit_launch(
        hd, cb.block, g=_paged._resolve_block_g(g, hd, dtype),
        dtype=dtype, nbm=cb.max_blocks,
        q_dtype=cb.gen._model_dtype())

    findings = []
    per_rule = {}
    for launch in launches:
        for f in audit_kernel_launch(launch, vmem_kib=vmem_kib):
            per_rule.setdefault(f.rule, f)
    for rule, f in sorted(per_rule.items()):
        findings.append(Finding(
            "VD705", f.severity, name,
            "paged-pool launch geometry (block=%d, resolved through "
            "config > tuner > default) fails %s: %s"
            % (cb.block, rule, f.message),
            hint=f.hint or "pin root.common.serve.paged_block to an "
                 "audited size, or re-bake the tuner winner"))
    return findings


def audit_prefill_pass(gen, segment=0, name=None):
    """VD700/VD702/VD703 over the segmented-prefill chunk pass — the
    OTHER jaxpr serving dispatches per admission
    (``LMGenerator._prefill_resume_fn``: the resume-from-cursor math
    both segmented admission and the prefix-cache compute skip run).
    ``segment`` sizes the chunk bucket (0 = one full-prompt pass)."""
    name = name or "prefill[%s]" % (getattr(gen, "weight_dtype", None)
                                    or "bf16")
    kb = gen._bucket(int(segment) or gen.max_len, gen.max_len)
    caches = jax.eval_shape(
        lambda: gen._init_caches(1, gen._model_dtype()))
    args = (_abstract(gen.params), caches,
            jax.ShapeDtypeStruct((1, kb), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.int32))
    try:
        closed = jax.make_jaxpr(gen._prefill_resume_fn(kb))(*args)
    except Exception as e:  # noqa: BLE001 — the failure IS the finding
        return [Finding(
            "VD702", ERROR, name,
            "segmented-prefill pass failed to trace abstractly: "
            "%s: %s" % (type(e).__name__, e),
            hint="the chunk pass must be traceable over "
                 "ShapeDtypeStructs")]
    return _scan_jaxpr(closed, name, params=gen.params,
                       scheme=getattr(gen, "weight_dtype", None))


#: the standard serving matrix ``lint_serving`` sweeps: weight scheme x
#: pool layout x speculative ticks — the same variants the chaos gates
#: exercise dynamically (tools/serve_loadtest.py legs).  Unsupported
#: combos on a given model (w4a8 under a model-axis mesh, quantized
#: MoE) are skipped, not findings — serving refuses them too.
DEFAULT_VARIANTS = (
    ("bf16/dense", {}),
    ("bf16/dense/spec", {"speculative_k": 4}),
    ("bf16/paged", {"paged": True}),
    ("int8/dense", {"weights": "int8"}),
    ("int8/paged-q8", {"weights": "int8", "cache_dtype": "int8",
                       "paged": True}),
    ("w4a8/dense", {"weights": "w4a8"}),
)


def lint_serving(trainer, max_len, variants=None, slots=2,
                 pool_tokens=None, prefill_segment=8, vmem_kib=None):
    """VD7xx audit of the real serving surface: build each variant's
    generator + batcher exactly as serving would (quantized weight
    copies ARE made — the same host-side construction work the engine
    does; no tick or prefill ever dispatches) and audit its tick, plus
    one segmented-prefill pass per weight scheme.  Returns sorted
    Findings."""
    from veles_tpu.models.generate import (ContinuousBatcher,
                                           LMGenerator,
                                           PagedContinuousBatcher)
    findings = []
    prefilled = set()
    for tag, spec in (variants or DEFAULT_VARIANTS):
        kwargs = dict(spec)
        paged = kwargs.pop("paged", False)
        spec_k = kwargs.pop("speculative_k", 0)
        try:
            gen = LMGenerator(trainer, max_len, **kwargs)
            if paged:
                cb = PagedContinuousBatcher(
                    gen, slots=slots,
                    pool_tokens=pool_tokens or slots * gen.max_len,
                    prefill_segment=prefill_segment)
            else:
                cb = ContinuousBatcher(
                    gen, slots=slots, speculative_k=spec_k,
                    prefill_segment=prefill_segment)
        except (TypeError, ValueError):
            continue      # variant unsupported on this model
        findings.extend(audit_decode_tick(cb, vmem_kib=vmem_kib,
                                          name="decode[%s]" % tag))
        scheme = kwargs.get("weights")
        if scheme not in prefilled:
            prefilled.add(scheme)
            findings.extend(audit_prefill_pass(
                gen, segment=prefill_segment,
                name="prefill[%s]" % (scheme or "bf16")))
    return sort_findings(findings)
