"""Serialized-state contract audit (VK10xx).

Every durability and agreement guarantee in this tree bottoms out in a
plain dict that crosses a process/time boundary: the training snapshot
(``collect`` -> pickle -> ``restore``/``reshard_state``), the commit
manifest sidecar (``state_manifest``/``commit_meta`` -> json ->
``scan_commits``/``validate_state_manifest``), the scan-report entries
the pod master's cross-host agreement ranks, the tuner's
``winners.json``, the flight recorder's crashdump ``meta.json``, the
fleet spawn spec, and the serving plane's NDJSON stream lines.  Nothing
checks those contracts: a key written that no restore path reads is
dead freight shipped in every checkpoint; a key read that no writer
sets resumes from a silent default; a strict subscript of an
optionally-written key KeyErrors on every pre-upgrade checkpoint.

This audit extracts the whole serialized-state universe from source
(pure AST — nothing is imported, nothing runs) and checks writers and
readers against each other.  The same extraction renders the
checked-in catalog ``docs/state_reference.md`` (``veles-tpu-lint
--state --format markdown``).

**Extraction model.**  Each *contract* (:data:`CONTRACTS`) names its
writer functions (dict literals that are returned, ``json.dump``-ed,
or NDJSON wire lines ``json.dumps(d) + "\\n"``; ``d["k"] = ...``
augmentation and ``dict(d, k=...)`` keywords add optional keys — as
does any ``d = <writer_func>(...)`` augmentation site anywhere in the
scanned files) and its reader functions (a named parameter or local
var: ``d["k"]`` strict reads, ``d.get("k")``, ``"k" in d`` probes;
*loose* readers contribute coverage only).  A key written under
``if``/``for``/``try`` is *optional*; strict subscripts of optional
keys need a probe (``"k" in d``), a prior ``.get``, or a version guard
(a comparison against the contract's version key) in the same
function.  Wall-clock provenance keys (:data:`META_KEYS`) and
contract-declared *external* keys (read by clients/operators outside
this tree) are exempt from the dead-freight rule, with their rationale
carried into the reference doc.

Rule catalog (docs/static_analysis.md):

========  =======  ======================================================
VK1000    warning  key written into a contract payload but read by no
                   restore/consumer path in the scanned tree — dead
                   freight that still costs wire/checkpoint bytes
VK1001    error    restore/consumer path reads a key no writer of that
                   contract ever sets — the silent-default resume-drift
                   class (``.get`` returns None forever)
VK1002    error    strict subscript of an optionally-written key with
                   no ``.get`` default, membership probe, or version
                   guard — KeyError on every old checkpoint (legacy-
                   compat break)
VK1003    error    non-canonical serialization feeding a digest or
                   compared artifact: ``json.dumps`` without
                   ``sort_keys=True`` flowing into ``hashlib``, or
                   dict-order iteration into a digest update
VK1004    error    pickled contract payload carries an unpicklable or
                   environment-bound value (lock/socket/thread/file
                   handles, lambdas) — the export dies, or worse,
                   resumes against a dead resource
========  =======  ======================================================

**Suppression**: ``# lint-ok: VK1002 — reason`` on the flagged line or
the contiguous comment block above it, exactly as for VT/VW/VC; a bare
``# lint-ok:`` suppresses nothing.
"""

import ast
import os
import re

from veles_tpu.analysis.findings import (ERROR, WARNING, Finding,
                                         sort_findings)

#: the full VK10xx family, in catalog order
RULES = ("VK1000", "VK1001", "VK1002", "VK1003", "VK1004")

_SUPPRESS_RE = re.compile(r"#\s*lint-ok:\s*([A-Z]{2}\d{3,4}(?:\s*,\s*"
                          r"[A-Z]{2}\d{3,4})*)")

#: wall-clock / provenance metadata keys every contract may carry:
#: written for operators and post-mortems, read by no restore path —
#: exempt from VK1000, with the rationale rendered into
#: docs/state_reference.md.
META_KEYS = {
    "created": "commit wall-time provenance for operators; never read "
               "back by any restore path",
    "mtime": "host-local commit mtime used only for same-host ordering "
             "(SPMD-lockstep ties are broken by name)",
    "ts": "crash wall-time provenance for the post-mortem timeline",
    "hostname": "which host wrote the commit — operator forensics",
    "pid": "writer pid — operator forensics",
}

#: per-contract discriminator keys (the wire dispatch tag — VW9xx's
#: domain, not dead freight)
_TAG_KEYS = ("type",)

_WALLCLOCK = ("time.time", "time.time_ns", "time.monotonic",
              "datetime.now", "datetime.utcnow",
              "datetime.datetime.now", "datetime.datetime.utcnow")

#: value shapes that must never ride a pickled contract payload
_UNPICKLABLE_NAME_RE = re.compile(
    r"(?:^|_)(lock|mutex|cond(?:ition)?|sock(?:et)?|conn(?:ection)?|"
    r"thread|pool|executor|server|queue|fh|file_?handle)s?$",
    re.IGNORECASE)
_UNPICKLABLE_CTORS = (
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Event", "threading.Semaphore", "threading.Thread",
    "socket.socket", "open")

#: serialized-state contracts: writer/reader function names are matched
#: by NAME across the scanned file set (cross-module contracts — e.g.
#: crashdump meta is written by telemetry/flight.py and read by
#: telemetry/blackbox.py).  Spec forms:
#:   writer {"func", "kind": "return"|"var"|"dump"|"wire"[, "var"]}
#:   reader {"func", "param": name} | {"func", "var": name}
#:          | {"func", "loose": True}   (coverage only — no VK1001/1002)
#: ``external`` maps client/operator-consumed keys (no in-tree reader)
#: to their rationale; ``version_key`` names the format-version tag
#: whose comparison counts as a guard.
CONTRACTS = (
    {"name": "snapshot.state",
     "doc": "pickled training state: collect() -> restore()/"
            "warm_start()/reshard_state()",
     "pickled": True,
     "version_key": None,
     "writers": ({"func": "collect", "kind": "return"},),
     "readers": ({"func": "restore", "param": "snapshot"},
                 {"func": "warm_start", "param": "snapshot"},
                 {"func": "reshard_state", "param": "state"},
                 {"func": "commit_meta", "param": "state"},
                 {"func": "validate_state_manifest", "param": "state"}),
     "external": {}},
    {"name": "commit.manifest",
     "doc": "json manifest sidecar: state_manifest()/commit_meta() -> "
            "scan_commits()/validate_state_manifest()/import paths",
     "pickled": False,
     "version_key": "format",
     "writers": ({"func": "state_manifest", "kind": "return"},
                 {"func": "commit_meta", "kind": "return"}),
     "readers": ({"func": "scan_commits", "var": "manifest"},
                 {"func": "validate_state_manifest",
                  "param": "manifest"},
                 {"func": "_import_file", "var": "manifest"},
                 {"func": "import_dir", "var": "manifest"},
                 {"func": "_flight_commit", "var": "meta"}),
     "external": {}},
    {"name": "commit.scan",
     "doc": "scan_commits() report entries ranked by cross-host "
            "agreement and rollback",
     "pickled": False,
     "version_key": None,
     "writers": ({"func": "scan_commits", "kind": "var",
                  "var": "entry"},),
     "readers": ({"func": "rollback_to_commit", "var": "entry"},
                 {"func": "agree_commits", "loose": True},
                 {"func": "_commit_order_key", "loose": True},
                 {"func": "_newest_healthy", "loose": True},
                 {"func": "_rollback_replay", "loose": True}),
     "external": {
         "incarnation": "which fenced incarnation committed — rendered "
                        "by the pod-master status surface",
         "process_index": "writer process — status surface / operators",
         "topology": "mesh shape of the committing run — the degraded-"
                     "resume accounting on the status surface",
         "error": "why a commit failed validation — operator "
                  "diagnostics in the status surface"}},
    {"name": "tuner.winners",
     "doc": "winners.json: Cache._save_locked() -> Cache._read_file()",
     "pickled": False,
     "version_key": "version",
     "writers": ({"func": "_save_locked", "kind": "dump"},),
     "readers": ({"func": "_read_file", "var": "data"},),
     "external": {}},
    {"name": "crashdump.meta",
     "doc": "crashdump meta.json: flight._meta_state() -> blackbox/"
            "supervisor post-mortem readers",
     "pickled": False,
     "version_key": None,
     "writers": ({"func": "_meta_state", "kind": "return"},),
     "readers": ({"func": "render_text", "var": "meta"},
                 {"func": "merge_timeline", "loose": True},
                 {"func": "_crashdump_error", "loose": True}),
     "external": {}},
    {"name": "fleet.spec",
     "doc": "worker spawn spec: PodMaster.worker_spec() -> agent "
            "_handle_spawn()/_wait_worker()/_heartbeat_loop()",
     "pickled": False,
     "version_key": None,
     "writers": ({"func": "worker_spec", "kind": "return"},),
     "readers": ({"func": "_handle_spawn", "param": "msg"},
                 {"func": "_wait_worker", "param": "spec"},
                 {"func": "_heartbeat_loop", "var": "spec"}),
     "external": {}},
    {"name": "serve.ndjson",
     "doc": "NDJSON stream lines: replica _do_work_post() -> router "
            "_pump_stream() -> client",
     "pickled": False,
     "version_key": None,
     "writers": ({"func": "_do_work_post", "kind": "wire"},
                 {"func": "_route_stream", "kind": "wire"},
                 {"func": "_pump_stream", "kind": "wire"}),
     "readers": ({"func": "_pump_stream", "var": "msg"},),
     "external": {
         "trace": "the client's cross-process reconstruction key "
                  "(veles-tpu-blackbox --trace)",
         "resumed": "client-visible failover-splice tag",
         "retry_after_s": "client backoff hint on the terminal error "
                          "line",
         "dropped_chunks": "client-visible drop-oldest overflow count "
                           "(the done line's result is authoritative)"}},
)

#: files (relative to the package root) that form the default
#: serialized-state universe
DEFAULT_FILES = (
    "services/snapshotter.py",
    "services/sentinel.py",
    "services/podmaster.py",
    "services/restful.py",
    "services/router.py",
    "services/supervisor.py",
    "tuner/cache.py",
    "telemetry/flight.py",
    "telemetry/blackbox.py",
)


def _dotted(node):
    """``a.b.c`` -> "a.b.c" (None for anything fancier)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _const_str(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class _Key(object):
    """One written contract key: where, and on which paths."""

    __slots__ = ("name", "rel", "lineno", "optional", "writer")

    def __init__(self, name, rel, lineno, optional, writer):
        self.name = name
        self.rel = rel
        self.lineno = lineno
        self.optional = optional
        self.writer = writer


class _Read(object):
    """One reader access: strict subscript, .get, or membership probe."""

    __slots__ = ("name", "rel", "lineno", "kind", "has_default",
                 "reader", "loose")

    def __init__(self, name, rel, lineno, kind, has_default, reader,
                 loose=False):
        self.name = name
        self.rel = rel
        self.lineno = lineno
        self.kind = kind              # "subscript" | "get" | "probe"
        self.has_default = has_default
        self.reader = reader
        self.loose = loose


class _Suppressor(object):
    """Line -> suppressed-rule lookup: a tag suppresses findings on its
    own line and on the first code line below a contiguous comment
    block (the VT/VW/VC semantics; a bare ``# lint-ok:`` is inert)."""

    def __init__(self, source):
        lines = source.splitlines()
        self._by_line = {}
        for i, line in enumerate(lines, 1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",")}
            self._by_line.setdefault(i, set()).update(rules)
            if line.lstrip().startswith("#"):
                j = i + 1
                while j <= len(lines) and \
                        lines[j - 1].lstrip().startswith("#"):
                    j += 1
                if j <= len(lines):
                    self._by_line.setdefault(j, set()).update(rules)

    def __call__(self, rule, lineno):
        return rule in self._by_line.get(lineno, ())


def _conditional_depth(func, target):
    """True when ``target`` executes only on some paths through
    ``func`` (nested under If/For/While/Try/With-in-If...)."""
    conditional = {}

    def walk(node, cond):
        for child in ast.iter_child_nodes(node):
            c = cond or isinstance(
                node, (ast.If, ast.For, ast.While, ast.Try,
                       ast.ExceptHandler))
            conditional[child] = c
            walk(child, c)

    walk(func, False)
    return conditional.get(target, False)


class _Module(object):
    """One parsed file: extraction + per-module rule checks."""

    def __init__(self, rel, tree, source):
        self.rel = rel
        self.tree = tree
        self.source = source
        self.suppressed = _Suppressor(source)
        self.findings = []
        #: every FunctionDef/AsyncFunctionDef in the file, by name
        #: (methods of any class included — names may repeat)
        self.functions = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                self.functions.setdefault(node.name, []).append(node)

    def _emit(self, rule, severity, lineno, message, hint=None):
        if self.suppressed(rule, lineno):
            return
        self.findings.append(Finding(
            rule, severity, "%s:%d" % (self.rel, lineno), message,
            hint=hint))

    # ------------------------------------------------------ writers
    def writer_keys(self, spec):
        """Extract the keys a writer function contributes, as _Key
        records (empty when the function is absent from this file)."""
        out = []
        for func in self.functions.get(spec["func"], ()):
            out.extend(self._keys_in(func, spec))
        return out

    def _keys_in(self, func, spec):
        kind = spec["kind"]
        dict_vars = {}        # name -> {key: (lineno, optional)}
        marked = set()        # vars that ARE the contract payload
        direct = []           # (keys, lineno) from anonymous literals

        def literal_keys(d):
            # literal keys are REQUIRED wherever the dict exists —
            # presence is judged relative to the dict's creation, not
            # the function entry (a literal built inside a loop still
            # always carries its keys); only augmentation
            # (``d["k"] = ...``, ``dict(d, k=...)``) is conditional
            keys = {}
            for k in d.keys:
                name = _const_str(k)
                if name is not None:
                    keys[name] = (k.lineno, False)
            return keys

        for node in ast.walk(func):
            optional = _conditional_depth(func, node)
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    if isinstance(node.value, ast.Dict):
                        dict_vars.setdefault(tgt.id, {}).update(
                            literal_keys(node.value))
                        if kind == "var" and tgt.id == spec.get("var"):
                            marked.add(tgt.id)
                    elif isinstance(node.value, ast.Call) and \
                            _dotted(node.value.func) == "dict":
                        ks = {kw.arg: (node.lineno, True)
                              for kw in node.value.keywords
                              if kw.arg}
                        dict_vars.setdefault(tgt.id, {}).update(ks)
                        if kind == "var" and tgt.id == spec.get("var"):
                            marked.add(tgt.id)
                elif isinstance(tgt, ast.Subscript) and \
                        isinstance(tgt.value, ast.Name):
                    key = _const_str(tgt.slice)
                    if key is not None:
                        dict_vars.setdefault(tgt.value.id, {}) \
                            .setdefault(key, (node.lineno, optional))
            elif isinstance(node, ast.Return) and kind == "return":
                if isinstance(node.value, ast.Name):
                    marked.add(node.value.id)
                elif isinstance(node.value, ast.Dict):
                    direct.append(literal_keys(node.value))
            elif isinstance(node, ast.Call):
                tail = (_dotted(node.func) or "").rsplit(".", 1)[-1]
                if kind == "dump" and tail in ("dump", "dumps") \
                        and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Name):
                        marked.add(arg.id)
                    elif isinstance(arg, ast.Dict):
                        direct.append(literal_keys(arg))
                elif kind == "wire" and tail == "dumps":
                    if self._is_wire_line(node):
                        arg = node.args[0] if node.args else None
                        if isinstance(arg, ast.Name):
                            marked.add(arg.id)
                        elif isinstance(arg, ast.Dict):
                            direct.append(literal_keys(arg))
        keys = []
        for var in marked:
            for name, (lineno, optional) in \
                    dict_vars.get(var, {}).items():
                keys.append(_Key(name, self.rel, lineno, optional,
                                 spec["func"]))
        for lk in direct:
            for name, (lineno, optional) in lk.items():
                keys.append(_Key(name, self.rel, lineno, optional,
                                 spec["func"]))
        return keys

    def _is_wire_line(self, dumps_call):
        """True when this json.dumps call feeds an NDJSON line: it sits
        (possibly under ``.encode()``) in a BinOp with a newline
        constant."""
        parents = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        node = dumps_call
        for _ in range(4):
            node = parents.get(node)
            if node is None:
                return False
            if isinstance(node, ast.BinOp):
                for side in (node.left, node.right):
                    if isinstance(side, ast.Constant) and \
                            side.value in ("\n", b"\n"):
                        return True
        return False

    def augmented_keys(self, writer_funcs):
        """``v = <writer_func>(...)`` anywhere, then ``v["k"] = ...``
        in the same function -> optional contract keys (the
        ``manifest["file_sha256"]`` / ``man["arrays"]`` idiom)."""
        keys = []
        for funcs in self.functions.values():
            for func in funcs:
                aliased = set()
                for node in ast.walk(func):
                    if isinstance(node, ast.Assign) and \
                            len(node.targets) == 1 and \
                            isinstance(node.targets[0], ast.Name) and \
                            isinstance(node.value, ast.Call):
                        callee = (_dotted(node.value.func) or "") \
                            .rsplit(".", 1)[-1]
                        if callee in writer_funcs:
                            aliased.add(node.targets[0].id)
                if not aliased:
                    continue
                for node in ast.walk(func):
                    if isinstance(node, ast.Assign) and \
                            len(node.targets) == 1 and \
                            isinstance(node.targets[0], ast.Subscript):
                        tgt = node.targets[0]
                        if isinstance(tgt.value, ast.Name) and \
                                tgt.value.id in aliased:
                            key = _const_str(tgt.slice)
                            if key is not None:
                                keys.append(_Key(
                                    key, self.rel, node.lineno, True,
                                    func.name))
        return keys

    # ------------------------------------------------------ readers
    def reader_accesses(self, spec):
        """All contract-key accesses a reader function performs, plus
        the keys it WRITES into the payload (reader-side augmentation
        like ``msg["resumed"] = True`` and ``dict(msg, k=...)``)."""
        reads, aug = [], []
        for func in self.functions.get(spec["func"], ()):
            r, a = self._accesses_in(func, spec)
            reads.extend(r)
            aug.extend(a)
        return reads, aug

    def _accesses_in(self, func, spec):
        loose = spec.get("loose", False)
        targets = set()
        if "param" in spec:
            targets.add(spec["param"])
        if "var" in spec:
            targets.add(spec["var"])

        def is_target(node):
            if loose:
                return True
            return isinstance(node, ast.Name) and node.id in targets

        reads, aug = [], []
        for node in ast.walk(func):
            if isinstance(node, ast.Subscript) and \
                    is_target(node.value):
                key = _const_str(node.slice)
                if key is None:
                    continue
                if isinstance(node.ctx, ast.Store):
                    if not loose:
                        aug.append(_Key(key, self.rel, node.lineno,
                                        True, spec["func"]))
                else:
                    reads.append(_Read(
                        key, self.rel, node.lineno, "subscript",
                        False, spec["func"], loose))
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "get" and node.args and \
                    is_target(node.func.value):
                key = _const_str(node.args[0])
                if key is not None:
                    reads.append(_Read(
                        key, self.rel, node.lineno, "get",
                        len(node.args) > 1, spec["func"], loose))
            elif isinstance(node, ast.Compare) and \
                    len(node.ops) == 1 and \
                    isinstance(node.ops[0], (ast.In, ast.NotIn)) and \
                    is_target(node.comparators[0]):
                key = _const_str(node.left)
                if key is not None:
                    reads.append(_Read(
                        key, self.rel, node.lineno, "probe",
                        False, spec["func"], loose))
            elif isinstance(node, ast.Call) and \
                    _dotted(node.func) == "dict" and node.args and \
                    is_target(node.args[0]) and not loose:
                for kw in node.keywords:
                    if kw.arg:
                        aug.append(_Key(kw.arg, self.rel, node.lineno,
                                        True, spec["func"]))
        return reads, aug

    def version_guarded(self, spec, version_key):
        """True when the reader function compares the contract's
        version key — every strict subscript in it is then guarded by
        the format check."""
        if version_key is None:
            return False
        for func in self.functions.get(spec["func"], ()):
            for node in ast.walk(func):
                if isinstance(node, ast.Compare):
                    for side in [node.left] + node.comparators:
                        if isinstance(side, ast.Subscript) and \
                                _const_str(side.slice) == version_key:
                            return True
                        if isinstance(side, ast.Call) and \
                                isinstance(side.func, ast.Attribute) \
                                and side.func.attr == "get" and \
                                side.args and \
                                _const_str(side.args[0]) == version_key:
                            return True
        return False

    # ---------------------------------------------- VK1003 / VK1004
    def check_canonical_digests(self):
        """VK1003: json.dumps without sort_keys feeding hashlib, and
        dict-order iteration into a digest update."""
        for funcs in self.functions.values():
            for func in funcs:
                self._check_digests_in(func)

    @staticmethod
    def _noncanonical_dumps(node):
        return (isinstance(node, ast.Call)
                and (_dotted(node.func) or "")
                .rsplit(".", 1)[-1] in ("dumps", "dump")
                and "json" in (_dotted(node.func) or "")
                and not any(kw.arg == "sort_keys"
                            for kw in node.keywords))

    def _check_digests_in(self, func):
        tainted = set()     # vars holding non-canonical json text
        hashes = set()      # vars holding hashlib objects
        for node in ast.walk(func):
            if isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                name, val = node.targets[0].id, node.value
                if any(self._noncanonical_dumps(n)
                       for n in ast.walk(val)):
                    tainted.add(name)
                elif isinstance(val, ast.Call) and \
                        (_dotted(val.func) or "") \
                        .startswith("hashlib."):
                    hashes.add(name)
                elif isinstance(val, ast.Call) and \
                        isinstance(val.func, ast.Attribute) and \
                        val.func.attr == "encode" and \
                        isinstance(val.func.value, ast.Name) and \
                        val.func.value.id in tainted:
                    tainted.add(name)

        def arg_tainted(arg):
            for n in ast.walk(arg):
                if self._noncanonical_dumps(n):
                    return True
                if isinstance(n, ast.Name) and n.id in tainted:
                    return True
            return False

        for node in ast.walk(func):
            if not isinstance(node, ast.Call):
                continue
            chain = _dotted(node.func) or ""
            is_digest_ctor = chain.startswith("hashlib.") or \
                chain == "hmac.new"
            is_update = isinstance(node.func, ast.Attribute) and \
                node.func.attr == "update" and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in hashes
            if (is_digest_ctor or is_update) and \
                    any(arg_tainted(a) for a in node.args):
                self._emit(
                    "VK1003", ERROR, node.lineno,
                    "non-canonical json.dumps feeds this digest — "
                    "dict insertion order varies across writers, so "
                    "equal states hash unequal",
                    hint="json.dumps(..., sort_keys=True) (canonical "
                         "form) before hashing")
        # dict-order iteration into a digest update
        for node in ast.walk(func):
            if not isinstance(node, ast.For):
                continue
            it = node.iter
            unordered = isinstance(it, (ast.Name, ast.Attribute)) or (
                isinstance(it, ast.Call)
                and isinstance(it.func, ast.Attribute)
                and it.func.attr in ("items", "keys", "values"))
            if not unordered:
                continue
            for inner in ast.walk(node):
                if isinstance(inner, ast.Call) and \
                        isinstance(inner.func, ast.Attribute) and \
                        inner.func.attr == "update" and \
                        isinstance(inner.func.value, ast.Name) and \
                        inner.func.value.id in hashes:
                    self._emit(
                        "VK1003", ERROR, inner.lineno,
                        "digest updated inside an insertion-order "
                        "dict iteration — equal states hash unequal "
                        "when written in a different order",
                        hint="iterate sorted(...) into the digest")
                    break

    def check_pickled_values(self, spec):
        """VK1004 over one pickled contract's writer functions."""
        for func in self.functions.get(spec["func"], ()):
            for node in ast.walk(func):
                if isinstance(node, ast.Dict):
                    for k, v in zip(node.keys, node.values):
                        key = _const_str(k)
                        if key is not None:
                            self._check_pickle_value(key, v)
                elif isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Subscript):
                    key = _const_str(node.targets[0].slice)
                    if key is not None:
                        self._check_pickle_value(key, node.value)

    def _check_pickle_value(self, key, value):
        bad = None
        if isinstance(value, ast.Lambda):
            bad = "a lambda (unpicklable closure)"
        elif isinstance(value, ast.Call):
            chain = _dotted(value.func) or ""
            if chain in _UNPICKLABLE_CTORS:
                bad = "a %s() instance" % chain
        elif isinstance(value, (ast.Name, ast.Attribute)):
            tail = value.id if isinstance(value, ast.Name) \
                else value.attr
            if _UNPICKLABLE_NAME_RE.search(tail):
                bad = "%r (an environment-bound handle by name)" % tail
        if bad is not None:
            self._emit(
                "VK1004", ERROR, value.lineno,
                "pickled state key %r carries %s — the export dies "
                "serializing it, or the restore resumes against a "
                "dead resource" % (key, bad),
                hint="keep runtime handles out of the payload; "
                     "reconstruct them in restore()")


def _parse(path, root=None):
    with open(path) as fh:
        source = fh.read()
    rel = os.path.relpath(path, root) if root else path
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return None, [Finding(
            "VK1001", ERROR, "%s:%d" % (rel, e.lineno or 0),
            "file failed to parse: %s" % e)]
    return _Module(rel, tree, source), []


def _default_paths():
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = os.path.dirname(here)
    return [os.path.join(here, f) for f in DEFAULT_FILES], root


class _ContractView(object):
    """One contract's extracted universe across the scanned modules."""

    def __init__(self, contract, modules):
        self.contract = contract
        self.keys = []          # _Key writers
        self.reads = []         # _Read accesses
        self.guard_keys = {}    # reader func -> probed/gotten keys
        self.version_guards = set()   # reader funcs with a format check
        self.has_reader = False
        self.has_writer = False
        writer_funcs = {w["func"] for w in contract["writers"]}
        for m in modules:
            for w in contract["writers"]:
                ks = m.writer_keys(w)
                if ks or m.functions.get(w["func"]):
                    self.has_writer = self.has_writer or \
                        bool(m.functions.get(w["func"]))
                self.keys.extend(ks)
            self.keys.extend(m.augmented_keys(writer_funcs))
            for r in contract["readers"]:
                if m.functions.get(r["func"]):
                    self.has_reader = True
                reads, aug = m.reader_accesses(r)
                self.reads.extend(reads)
                self.keys.extend(aug)
                if m.version_guarded(r, contract["version_key"]):
                    self.version_guards.add(r["func"])
        for read in self.reads:
            if read.kind in ("get", "probe"):
                self.guard_keys.setdefault(read.reader, set()) \
                    .add(read.name)

    @property
    def written(self):
        """key -> _Key (first writer site; optional iff EVERY site is
        optional — a key any writer always sets is required)."""
        out = {}
        for k in self.keys:
            prev = out.get(k.name)
            if prev is None:
                out[k.name] = k
            elif prev.optional and not k.optional:
                out[k.name] = k
        return out

    @property
    def read_names(self):
        return {r.name for r in self.reads}


def lint_state(paths=None, root=None):
    """VK10xx over a file set — default :data:`DEFAULT_FILES` under the
    package root.  The scanned files form ONE serialized-state
    universe: a contract written in one module and read in another is
    matched across them.  Returns sorted Findings; inline ``# lint-ok:
    VKxxxx — reason`` comments suppress accepted sites."""
    if paths is None:
        paths, droot = _default_paths()
        root = root or droot
    findings, modules = [], []
    for p in paths:
        mod, errs = _parse(p, root=root)
        findings.extend(errs)
        if mod is not None:
            modules.append(mod)

    for contract in CONTRACTS:
        view = _ContractView(contract, modules)
        written = view.written
        read_names = view.read_names
        exempt = set(META_KEYS) | set(contract["external"]) \
            | set(_TAG_KEYS)
        if contract["version_key"]:
            exempt.add(contract["version_key"])
        by_rel = {m.rel: m for m in modules}
        # VK1000: dead freight (only when the universe includes at
        # least one reader — a partial view cannot judge deadness)
        if view.has_reader:
            for name in sorted(written):
                if name in read_names or name in exempt:
                    continue
                k = written[name]
                by_rel[k.rel]._emit(
                    "VK1000", WARNING, k.lineno,
                    "contract %s: key %r is written here but no "
                    "restore/consumer path in the scanned tree reads "
                    "it — dead freight in every %s payload"
                    % (contract["name"], name,
                       "pickle" if contract["pickled"] else "wire/"
                       "json"),
                    hint="drop the key, add the missing reader, or "
                         "declare it in the contract's external/"
                         "META_KEYS exemptions with a rationale")
        # VK1001 / VK1002: reader-side checks need at least one writer
        if view.has_writer:
            for read in view.reads:
                if read.loose:
                    continue
                mod = by_rel[read.rel]
                if read.name not in written:
                    mod._emit(
                        "VK1001", ERROR, read.lineno,
                        "contract %s: %r is read here but no writer "
                        "of the contract ever sets it — this path "
                        "resumes from a silent default forever"
                        % (contract["name"], read.name),
                        hint="set the key at every writer, or delete "
                             "the stale read")
                    continue
                key = written[read.name]
                if read.kind == "subscript" and key.optional and \
                        read.name not in view.guard_keys.get(
                            read.reader, ()) and \
                        read.reader not in view.version_guards:
                    mod._emit(
                        "VK1002", ERROR, read.lineno,
                        "contract %s: strict subscript of optionally-"
                        "written key %r with no .get default, "
                        "membership probe, or version guard — "
                        "KeyError on every payload from before the "
                        "key existed" % (contract["name"], read.name),
                        hint="use .get(%r, default), probe with "
                             "'%s in ...', or gate on the contract's "
                             "version key" % (read.name, read.name))
        # VK1004 over pickled contracts' writer payloads
        if contract["pickled"]:
            for m in modules:
                for w in contract["writers"]:
                    m.check_pickled_values(w)

    for m in modules:
        m.check_canonical_digests()
        findings.extend(m.findings)
    return sort_findings(findings)


def build_reference(root=None):
    """Render ``docs/state_reference.md``: every serialized contract
    key with its writers, readers, presence, and version notes —
    byte-deterministic (the CI freshness diff depends on it)."""
    paths, droot = _default_paths()
    modules = []
    for p in paths:
        mod, _ = _parse(p, root=root or droot)
        if mod is not None:
            modules.append(mod)
    out = [
        "# Serialized-state contract reference",
        "",
        "Generated by `veles-tpu-lint --state --format markdown` "
        "(analysis/state_audit.py) — do not edit by hand; CI diffs "
        "this file against a fresh render.  Every key that crosses a "
        "process or time boundary: who writes it, who reads it back, "
        "and why the unread ones are not dead freight.  The VK10xx "
        "rule catalog lives in docs/static_analysis.md.",
        "",
    ]
    for contract in CONTRACTS:
        view = _ContractView(contract, modules)
        written = view.written
        readers_by_key = {}
        for r in view.reads:
            readers_by_key.setdefault(r.name, set()).add(
                "%s:%s" % (os.path.basename(r.rel), r.reader))
        out.append("## %s" % contract["name"])
        out.append("")
        out.append("%s.  Serialization: %s." % (
            contract["doc"],
            "pickle" if contract["pickled"] else "json"))
        if contract["version_key"]:
            out.append("Version key: `%s` — readers comparing it are "
                       "version-guarded (VK1002)."
                       % contract["version_key"])
        out.append("")
        out.append("| key | presence | writers | readers | notes |")
        out.append("|---|---|---|---|---|")
        for name in sorted(written):
            k = written[name]
            readers = sorted(readers_by_key.get(name, ()))
            notes = ""
            if name in contract["external"]:
                notes = "external: %s" % contract["external"][name]
            elif name in META_KEYS:
                notes = "metadata: %s" % META_KEYS[name]
            elif name == contract["version_key"]:
                notes = "format-version tag"
            elif name in _TAG_KEYS:
                notes = "wire dispatch tag (VW9xx's domain)"
            out.append("| `%s` | %s | %s | %s | %s |" % (
                name,
                "optional" if k.optional else "required",
                "%s:%s" % (os.path.basename(k.rel), k.writer),
                ", ".join(readers) if readers else "—",
                notes))
        out.append("")
    return "\n".join(out) + "\n"
