"""Finding records emitted by the static analyzers.

Every rule yields structured :class:`Finding` objects — rule id, severity,
unit, message, fix hint — so the CLI can render text or JSON and CI can
gate on severity without parsing prose.  Rule catalog: docs/static_analysis.md."""

import dataclasses

#: severities, most severe first (the order drives sorting and the
#: exit-code gate: only ERROR findings fail `veles-tpu-lint` / `--lint`)
ERROR = "error"
WARNING = "warning"
INFO = "info"
SEVERITIES = (ERROR, WARNING, INFO)


@dataclasses.dataclass
class Finding:
    rule: str          #: catalog id, e.g. "VG001" (graph) / "VJ101" (jit)
    severity: str      #: one of SEVERITIES
    unit: str          #: offending unit's name, or "<workflow>" / "<step>"
    message: str       #: one-line statement of the defect
    hint: str = ""     #: how to fix it

    def __str__(self):
        s = "[%s %s] %s: %s" % (self.rule, self.severity, self.unit,
                                self.message)
        if self.hint:
            s += "\n    hint: %s" % self.hint
        return s

    def as_dict(self):
        return dataclasses.asdict(self)


def sort_findings(findings):
    """Most severe first, then by rule id, then unit — a stable order for
    humans and golden tests alike."""
    rank = {s: i for i, s in enumerate(SEVERITIES)}
    return sorted(findings,
                  key=lambda f: (rank.get(f.severity, len(SEVERITIES)),
                                 f.rule, f.unit))


def has_errors(findings):
    return any(f.severity == ERROR for f in findings)


def threshold_reached(findings, fail_on=ERROR):
    """The ONE exit-code gate every lint surface shares
    (``veles-tpu-lint`` and ``python -m veles_tpu --lint``): True when
    any finding is at or above the ``fail_on`` severity — so
    ``--fail-on`` means the same thing whether the findings came from
    the graph, staging, sharding, or numerics passes.  Exit codes:
    0 = below threshold, 1 = threshold reached, 2 = usage error."""
    if fail_on not in SEVERITIES:
        raise ValueError("fail_on must be one of %r, got %r"
                         % (SEVERITIES, fail_on))
    allowed = SEVERITIES[:SEVERITIES.index(fail_on) + 1]
    return any(f.severity in allowed for f in findings)


def format_findings(findings, fmt="text"):
    findings = sort_findings(findings)
    if fmt == "json":
        import json
        return json.dumps([f.as_dict() for f in findings], indent=2)
    if not findings:
        return "no findings"
    counts = {}
    for f in findings:
        counts[f.severity] = counts.get(f.severity, 0) + 1
    tally = ", ".join("%d %s%s" % (n, s, "s" if n != 1 else "")
                      for s in SEVERITIES for n in [counts.get(s, 0)] if n)
    return "\n".join(str(f) for f in findings) + "\n-- %s" % tally
